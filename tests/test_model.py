"""Model assembly tests: shapes for every flag combination, scan==unroll."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward

B, H, W = 1, 64, 96


def make_inputs(rng, h=H, w=W):
    img1 = jnp.asarray(rng.uniform(0, 255, size=(B, h, w, 3)).astype(np.float32))
    img2 = jnp.asarray(rng.uniform(0, 255, size=(B, h, w, 3)).astype(np.float32))
    return img1, img2


def test_forward_train_mode_shapes(rng):
    cfg = RAFTStereoConfig()
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1, img2 = make_inputs(rng)
    preds = raft_stereo_forward(params, cfg, img1, img2, iters=3)
    assert preds.shape == (3, B, H, W, 1)
    assert np.isfinite(np.asarray(preds)).all()


def test_forward_test_mode_shapes(rng):
    cfg = RAFTStereoConfig()
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1, img2 = make_inputs(rng)
    flow_lr, flow_up = raft_stereo_forward(params, cfg, img1, img2, iters=3,
                                           test_mode=True)
    assert flow_lr.shape == (B, H // 4, W // 4, 2)
    assert flow_up.shape == (B, H, W, 1)


def test_scan_matches_unroll(rng):
    cfg = RAFTStereoConfig()
    params = init_raft_stereo(jax.random.key(1), cfg)
    img1, img2 = make_inputs(rng)
    preds_scan = raft_stereo_forward(params, cfg, img1, img2, iters=4)
    preds_unroll = raft_stereo_forward(params, cfg, img1, img2, iters=4,
                                       unroll=True)
    # scan and unroll compile to differently-fused programs; fp reassociation
    # noise (~3e-5 per step on CPU/oneDNN) is amplified by the recurrence, so
    # the bound is loose — semantic equivalence is what is being tested.
    np.testing.assert_allclose(np.asarray(preds_scan), np.asarray(preds_unroll),
                               atol=1e-2)


def test_flow_init_shifts_result(rng):
    cfg = RAFTStereoConfig()
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1, img2 = make_inputs(rng)
    flow_lr0, _ = raft_stereo_forward(params, cfg, img1, img2, iters=2,
                                      test_mode=True)
    init = jnp.zeros_like(flow_lr0) - 3.0
    flow_lr1, _ = raft_stereo_forward(params, cfg, img1, img2, iters=2,
                                      flow_init=init, test_mode=True)
    assert not np.allclose(np.asarray(flow_lr0), np.asarray(flow_lr1))


@pytest.mark.parametrize("n_gru_layers", [1, 2, 3])
@pytest.mark.parametrize("n_downsample", [2, 3])
@pytest.mark.parametrize("shared_backbone", [False, True])
@pytest.mark.parametrize("slow_fast_gru", [False, True])
def test_all_flag_combinations_wire_up(n_gru_layers, n_downsample,
                                       shared_backbone, slow_fast_gru):
    """eval_shape-based wiring test: every flag combination must trace."""
    cfg = RAFTStereoConfig(n_gru_layers=n_gru_layers, n_downsample=n_downsample,
                           shared_backbone=shared_backbone,
                           slow_fast_gru=slow_fast_gru)
    params = jax.eval_shape(lambda k: init_raft_stereo(k, cfg), jax.random.key(0))

    def fwd(params, img1, img2):
        return raft_stereo_forward(params, cfg, img1, img2, iters=2)

    img = jax.ShapeDtypeStruct((B, 32, 64, 3), jnp.float32)
    out = jax.eval_shape(fwd, params, img, img)
    assert out.shape == (2, B, 32, 64, 1)


def test_mixed_precision_runs(rng):
    cfg = RAFTStereoConfig(mixed_precision=True)
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1, img2 = make_inputs(rng)
    preds = raft_stereo_forward(params, cfg, img1, img2, iters=2)
    assert np.isfinite(np.asarray(preds, dtype=np.float32)).all()
    # Predictions accumulate in fp32 regardless of compute dtype.
    assert preds.dtype == jnp.float32


@pytest.mark.parametrize("impl", ["reg", "alt", "reg_tpu", "alt_tpu"])
def test_corr_impl_equivalence_end_to_end(rng, impl):
    cfg_reg = RAFTStereoConfig(corr_implementation="reg")
    cfg_imp = RAFTStereoConfig(corr_implementation=impl)
    params = init_raft_stereo(jax.random.key(2), cfg_reg)
    img1, img2 = make_inputs(rng)
    out_reg = raft_stereo_forward(params, cfg_reg, img1, img2, iters=2)
    out_imp = raft_stereo_forward(params, cfg_imp, img1, img2, iters=2)
    # reg and alt associate the dot/pool differently; recurrence amplifies fp
    # noise slightly (see test_scan_matches_unroll).
    np.testing.assert_allclose(np.asarray(out_reg), np.asarray(out_imp), atol=1e-3)


def test_sequential_fnet_matches_concat(rng, monkeypatch):
    """The full-res lax.map fnet path must equal the batch-concat path.

    Instance norm is per-sample, so running the two images sequentially is
    semantically identical; this pins it (the threshold constant means the
    sequential branch is otherwise only compiled at >=2M-pixel shapes).
    """
    from raft_stereo_tpu.models import raft_stereo as rs

    cfg = RAFTStereoConfig()
    params = init_raft_stereo(jax.random.key(2), cfg)
    img1, img2 = make_inputs(rng)
    _, up_concat = raft_stereo_forward(params, cfg, img1, img2, iters=2,
                                       test_mode=True)
    monkeypatch.setattr(rs, "FNET_SEQUENTIAL_MIN_PIXELS", 0)
    _, up_seq = raft_stereo_forward(params, cfg, img1, img2, iters=2,
                                    test_mode=True)
    # Differently-fused compilations: fp reassociation only (rel ~2e-6).
    np.testing.assert_allclose(np.asarray(up_seq), np.asarray(up_concat),
                               rtol=1e-5, atol=1e-3)

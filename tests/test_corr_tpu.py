"""TPU-hardware-only checks for the Pallas corr kernels.

Skipped on the CPU test topology (tests/conftest.py forces CPU); run
manually on a TPU host: ``JAX_PLATFORMS='' python -m pytest tests/test_corr_tpu.py``
with conftest's platform pin overridden, or via ``scratch/`` drivers.
The numeric parity of compiled-Mosaic vs XLA is asserted here; the same
properties are covered in interpret mode by tests/test_corr.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.corr import make_corr_fn

pytestmark = pytest.mark.skipif(jax.default_backend() != "tpu",
                                reason="requires TPU hardware")

LEVELS, RADIUS = 4, 4


def test_compiled_kernels_match_reg_wide():
    rng = np.random.default_rng(0)
    b, h, w, d = 1, 8, 376, 32
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    reg = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    for impl in ("reg_tpu", "alt_tpu"):
        out = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)(
            coords)
        np.testing.assert_allclose(np.asarray(out), np.asarray(reg),
                                   atol=2e-2)  # MXU default-precision matmul


def test_alt_tpu_memory_is_bounded():
    """The fused kernel must not materialize the O(H*W^2) volume in HBM.

    At Middlebury-F quarter-res the reg_tpu volume pyramid is ~2.3 GB of
    temps; alt_tpu's temps are the padded f2 pyramid — O(H*W*D), linear in
    W — plus per-row VMEM blocks. Asserted as a ratio against the compiled
    reg_tpu program at the same shape (compile-only; nothing is executed).
    """
    b, h, w, d = 1, 504, 744, 256

    def run(impl, f1, f2, coords):
        return make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)(
            coords)

    args = (jax.ShapeDtypeStruct((b, h, w, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, w, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, w), jnp.float32))

    def temp_bytes(impl):
        lowered = jax.jit(lambda f1, f2, c: run(impl, f1, f2, c)).lower(*args)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    alt_temp = temp_bytes("alt_tpu")
    reg_temp = temp_bytes("reg_tpu")
    assert alt_temp < reg_temp / 2, (alt_temp, reg_temp)
    # Absolute bound, linear in W (measured 2.03x at this shape): temps are
    # the padded f2 copy + layout copies of O(H*W*D). Materializing even one
    # bf16 W^2 volume level (~0.55 GB here) on top would breach it.
    fmap_bytes = 4 * h * w * d
    assert alt_temp < 2.5 * fmap_bytes, (alt_temp, fmap_bytes)


def test_compiled_kernel_grads_match_reg():
    """custom_vjp backward vs XLA autodiff through reg, on hardware."""
    rng = np.random.default_rng(1)
    b, h, w, d = 1, 8, 200, 32
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(0, w - 1, size=(b, h, w)).astype(np.float32))
    cot = jnp.asarray(rng.standard_normal((b, h, w, 36), dtype=np.float32))

    def loss(impl, a, bb):
        out = make_corr_fn(impl, a, bb, num_levels=LEVELS, radius=RADIUS)(
            coords)
        return jnp.sum(out * cot)

    g_reg = jax.jit(jax.grad(lambda a, bb: loss("reg", a, bb),
                             argnums=(0, 1)))(f1, f2)
    for impl in ("reg_tpu", "alt_tpu"):
        g = jax.jit(jax.grad(lambda a, bb: loss(impl, a, bb),
                             argnums=(0, 1)))(f1, f2)
        for ga, gb in zip(g, g_reg):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                       atol=5e-2)  # MXU matmul precision


def test_compiled_kernels_bf16_inputs():
    """bf16 fmaps (the mixed-precision path) through compiled Mosaic.

    The fp32 tests above cannot catch bf16-only Mosaic limitations (e.g.
    dynamic_gather's bitwidth-match requirement); this pins the exact
    dtype combination the bench/mixed-precision eval runs.
    """
    rng = np.random.default_rng(2)
    b, h, w, d = 1, 8, 376, 32
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d)), jnp.bfloat16)
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d)), jnp.bfloat16)
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    reg = make_corr_fn("reg", f1.astype(jnp.float32), f2.astype(jnp.float32),
                       num_levels=LEVELS, radius=RADIUS)(coords)
    for impl in ("reg_tpu", "alt_tpu"):
        out = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)(
            coords)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(reg),
                                   atol=0.15)  # bf16 input quantization

"""Property tests for the correlation implementations.

One protocol, interchangeable outputs: all implementations must agree on random
inputs; ``reg`` is additionally checked against a naive python-loop oracle, and
gradients are checked to flow into the feature maps (the reference's custom
CUDA backward propagates to the volume only; coords are detached upstream each
iteration, ``core/raft_stereo.py:109``, so no coord gradient is required).
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.corr import make_corr_fn

# Correlation oracle battery: compiled-on-TPU via RAFT_TEST_ONCHIP=1
# (scripts/run_onchip_battery.sh), interpret-mode on CPU otherwise.
pytestmark = pytest.mark.kernel_battery
from raft_stereo_tpu.corr.reg import build_pyramid, build_volume, lookup_pyramid

B, H, W, D = 2, 6, 32, 16
LEVELS, RADIUS = 4, 4


@pytest.fixture
def fmaps(rng):
    f1 = jnp.asarray(rng.standard_normal((B, H, W, D), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((B, H, W, D), dtype=np.float32))
    return f1, f2


@pytest.fixture
def coords(rng):
    # Fractional positions, some outside [0, W-1] to exercise zero padding.
    return jnp.asarray(rng.uniform(-4, W + 3, size=(B, H, W)).astype(np.float32))


def naive_lookup(f1, f2, coords_x, num_levels, radius):
    """Straight-line oracle: explicit volume, loop gather with zero pad."""
    f1, f2, coords_x = map(np.asarray, (f1, f2, coords_x))
    d = f1.shape[-1]
    vol = np.einsum("bhid,bhjd->bhij", f1, f2) / math.sqrt(d)
    outs = []
    for lvl in range(num_levels):
        w2 = vol.shape[-1]
        for off in range(-radius, radius + 1):
            x = coords_x / (2 ** lvl) + off
            x0 = np.floor(x).astype(int)
            frac = x - x0
            v0 = np.where((x0 >= 0) & (x0 < w2),
                          np.take_along_axis(vol, np.clip(x0, 0, w2 - 1)[..., None],
                                             axis=-1)[..., 0], 0.0)
            v1 = np.where((x0 + 1 >= 0) & (x0 + 1 < w2),
                          np.take_along_axis(vol, np.clip(x0 + 1, 0, w2 - 1)[..., None],
                                             axis=-1)[..., 0], 0.0)
            outs.append(v0 * (1 - frac) + v1 * frac)
        # next level: pool volume width by 2
        w2e = (w2 // 2) * 2
        vol = vol[..., :w2e].reshape(*vol.shape[:-1][:3], w2 // 2, 2).mean(-1)
    return np.stack(outs, axis=-1)


def test_reg_matches_naive(fmaps, coords):
    f1, f2 = fmaps
    corr_fn = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)
    out = corr_fn(coords)
    ref = naive_lookup(f1, f2, coords, LEVELS, RADIUS)
    assert out.shape == (B, H, W, LEVELS * (2 * RADIUS + 1))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)


@pytest.mark.parametrize("impl", ["alt", "reg_tpu", "reg_cuda", "alt_tpu",
                                  "alt_cuda"])
def test_impls_match_reg(fmaps, coords, impl):
    f1, f2 = fmaps
    reg = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    out = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reg), atol=1e-4)


@pytest.mark.parametrize("impl", ["reg", "alt", "reg_tpu", "alt_tpu"])
def test_out_dtype_bf16(fmaps, coords, impl):
    """out_dtype=bf16: the kernels downcast in-kernel (fp32 lerp arithmetic
    retained), the XLA paths fuse the convert — all four must agree with the
    fp32 path to bf16 rounding."""
    f1, f2 = fmaps
    ref = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    out = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS,
                       out_dtype=jnp.bfloat16)(coords)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.02, rtol=0.01)


@pytest.mark.parametrize("impl", ["reg_tpu", "alt_tpu"])
def test_out_dtype_bf16_grads_flow(fmaps, coords, impl):
    """custom_vjp with a bf16 cotangent: grads reach the fmaps, finite."""
    f1, f2 = fmaps

    def loss(f1, f2):
        fn = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS,
                          out_dtype=jnp.bfloat16)
        return jnp.sum(fn(coords).astype(jnp.float32) ** 2)

    g1, g2 = jax.grad(loss, argnums=(0, 1))(f1, f2)
    assert np.isfinite(np.asarray(g1)).all() and np.abs(g1).sum() > 0
    assert np.isfinite(np.asarray(g2)).all() and np.abs(g2).sum() > 0


@pytest.mark.parametrize("impl", ["reg_tpu", "alt_tpu"])
@pytest.mark.parametrize("w", [200, 376])
def test_tpu_impls_match_reg_wide(rng, impl, w):
    """Wide rows exercise the kernels' coarse window-align path (W2p > 128)."""
    b, h, d = 1, 4, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    reg = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    out = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reg), atol=1e-4)


@pytest.mark.parametrize("impl", ["reg", "alt", "reg_tpu", "alt_tpu"])
def test_grads_flow_to_fmaps(fmaps, coords, impl):
    f1, f2 = fmaps

    def loss(f1, f2):
        corr_fn = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)
        return jnp.sum(corr_fn(coords) ** 2)

    g1, g2 = jax.grad(loss, argnums=(0, 1))(f1, f2)
    assert np.isfinite(np.asarray(g1)).all() and np.isfinite(np.asarray(g2)).all()
    assert float(jnp.abs(g1).max()) > 0 and float(jnp.abs(g2).max()) > 0


@pytest.mark.parametrize("impl", ["reg", "alt", "reg_tpu", "alt_tpu"])
def test_grad_matches_across_impls(fmaps, coords, impl):
    """reg and alt must have identical gradients (they are the same function)."""
    f1, f2 = fmaps

    def loss_with(impl_name):
        def loss(f1, f2):
            corr_fn = make_corr_fn(impl_name, f1, f2, num_levels=LEVELS, radius=RADIUS)
            return jnp.mean(corr_fn(coords) ** 2)
        return jax.grad(loss, argnums=(0, 1))(f1, f2)

    g_reg = loss_with("reg")
    g_imp = loss_with(impl)
    for a, b in zip(jax.tree.leaves(g_reg), jax.tree.leaves(g_imp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("h,h_chunk", [(64, 16), (40, 16)])
def test_alt_chunked_matches_reg(rng, h, h_chunk):
    """The H-chunked lax.map path must reassemble rows in order.

    Regression: chunk slices arrive as (B, h_chunk, ...) already; an extra
    moveaxis inside the map body scrambled batch/row axes whenever
    h % h_chunk == 0 (e.g. KITTI eval at H/4 = 96). Covers both the exact
    multiple and the padded (h % h_chunk != 0) path, with b > 1 and
    multiple chunks.
    """
    b, w = 2, 24
    f1 = jnp.asarray(rng.standard_normal((b, h, w, D), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, D), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-4, w + 3, size=(b, h, w)).astype(np.float32))
    reg = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    alt = make_corr_fn("alt", f1, f2, num_levels=LEVELS, radius=RADIUS)(
        coords, h_chunk=h_chunk)
    np.testing.assert_allclose(np.asarray(alt), np.asarray(reg), atol=1e-4)


def test_pyramid_shapes(fmaps):
    f1, f2 = fmaps
    pyr = build_pyramid(build_volume(f1, f2), LEVELS)
    assert [p.shape[-1] for p in pyr] == [W, W // 2, W // 4, W // 8]


@pytest.mark.parametrize("impl", ["reg", "reg_tpu", "alt_tpu"])
def test_lookup_under_jit_and_scan(fmaps, coords, impl):
    """The closure must be capturable by lax.scan (the GRU-loop requirement)."""
    f1, f2 = fmaps
    corr_fn = make_corr_fn(impl, f1, f2, num_levels=LEVELS, radius=RADIUS)

    @jax.jit
    def run(coords0):
        def step(c, _):
            out = corr_fn(c)
            return c + 0.1, jnp.mean(out)
        _, ys = jax.lax.scan(step, coords0, None, length=4)
        return ys

    ys = run(coords)
    assert ys.shape == (4,)
    assert np.isfinite(np.asarray(ys)).all()


@pytest.mark.parametrize("w", [32, 200, 376, 640])
def test_reg_tpu_packed_bf16_matches_reg(rng, w):
    """bf16 fmaps engage the pair-packed lookup (two bf16 taps per 32-bit
    lane, fp32-container rows): must match the fp32 reg path to bf16
    rounding. Widths cover single-vreg, two-slab and multi-slab packed
    rows (w=640 -> 768-wide padded bf16 = 3 packed i32 slabs at level 0)."""
    b, h, d = 1, 4, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    ref = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    out = make_corr_fn("reg_tpu", f1.astype(jnp.bfloat16),
                       f2.astype(jnp.bfloat16), num_levels=LEVELS,
                       radius=RADIUS)(coords)
    # bf16 fmaps change the volume einsum inputs too; tolerance covers the
    # bf16 volume, not just the packed tap transport.
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.25, rtol=0.05)


def test_reg_tpu_packed_exact_vs_unpacked_taps(rng):
    """The packed gather transports the SAME bf16 tap values as the
    unpacked path — bit-exact agreement between a bf16-volume reg_tpu
    lookup and the masked one-hot oracle run on the identical bf16 rows."""
    from raft_stereo_tpu.corr.pallas_reg import (
        _masked_lookup_xla, level_widths, make_reg_tpu_corr_fn, pad_width)
    from raft_stereo_tpu.corr.reg import build_pyramid
    b, h, w, d = 1, 3, 200, 16
    f1 = jnp.asarray(
        rng.standard_normal((b, h, w, d), dtype=np.float32)).astype(
            jnp.bfloat16)
    f2 = jnp.asarray(
        rng.standard_normal((b, h, w, d), dtype=np.float32)).astype(
            jnp.bfloat16)
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    out = make_reg_tpu_corr_fn(f1, f2, num_levels=LEVELS,
                               radius=RADIUS)(coords)
    # Rebuild the identical bf16 rows the kernel saw and run the oracle.
    widths = level_widths(w, LEVELS)
    f2p = jnp.pad(f2, ((0, 0), (0, 0), (0, pad_width(w) - w), (0, 0)))
    vol = jnp.einsum("bhid,bhjd->bhij", f1, f2p) * (1.0 / d ** 0.5)
    rows = []
    for lvl, v in enumerate(build_pyramid(vol, LEVELS)):
        want = -(-widths[lvl] // 256) * 256
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, want - v.shape[-1])))
        rows.append(v.reshape(b, h * w, -1))
    ref = _masked_lookup_xla(rows, coords.reshape(b, h * w, 1), RADIUS,
                             widths).reshape(b, h, w, -1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_reg_tpu_packed_grads_flow_bf16_fmaps(rng):
    """Grads traverse pack_rows' bit-transport vjp back to bf16 fmaps."""
    b, h, w, d = 1, 4, 200, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-4, w + 3, size=(b, h, w)).astype(np.float32))

    def loss(f1_, f2_):
        fn = make_corr_fn("reg_tpu", f1_.astype(jnp.bfloat16),
                          f2_.astype(jnp.bfloat16), num_levels=LEVELS,
                          radius=RADIUS)
        return jnp.sum(fn(coords).astype(jnp.float32) ** 2)

    g1, g2 = jax.grad(loss, argnums=(0, 1))(f1, f2)
    assert np.isfinite(np.asarray(g1)).all() and np.abs(g1).sum() > 0
    assert np.isfinite(np.asarray(g2)).all() and np.abs(g2).sum() > 0


def test_reg_tpu_packed_multi_call_grad_linearity(rng):
    """Cotangents must sum LINEARLY across multiple lookups of one corr fn
    (the GRU loop runs 32): grad of a two-call loss == sum of single-call
    grads. Regression: routing grads through the fp32 bit-containers made
    JAX sum packed cotangents as ordinary floats -> NaN/garbage."""
    b, h, w, d = 1, 4, 200, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    c1 = jnp.asarray(
        rng.uniform(-4, w + 3, size=(b, h, w)).astype(np.float32))
    c2 = jnp.asarray(
        rng.uniform(-4, w + 3, size=(b, h, w)).astype(np.float32))

    def loss(f1_, f2_, coords_list):
        fn = make_corr_fn("reg_tpu", f1_.astype(jnp.bfloat16),
                          f2_.astype(jnp.bfloat16), num_levels=LEVELS,
                          radius=RADIUS)
        return sum(jnp.sum(fn(c).astype(jnp.float32) ** 2)
                   for c in coords_list)

    g_both = jax.grad(loss, argnums=(0, 1))(f1, f2, [c1, c2])
    g_1 = jax.grad(loss, argnums=(0, 1))(f1, f2, [c1])
    g_2 = jax.grad(loss, argnums=(0, 1))(f1, f2, [c2])
    for gb, ga, gc in zip(g_both, g_1, g_2):
        gb, ga, gc = map(np.asarray, (gb, ga, gc))
        assert np.isfinite(gb).all()
        scale = np.abs(ga + gc).max() + 1e-6
        assert np.abs(gb - (ga + gc)).max() / scale < 0.05


def test_pack_plan_combines_odd_block_levels():
    """The packing rule: even-128-block widths pack standalone; the widest
    and deepest ODD-block widths share one combined container (zero pad
    bloat); any further odd-block level stays plain. Middlebury-F's
    744-wide pyramid is the motivating case: L0+L2 standalone, L1 hosts
    L3's 64-lane tail — every level packed, total DMA unchanged."""
    from raft_stereo_tpu.corr.pallas_reg import level_widths, pack_plan
    assert pack_plan(level_widths(744, 4), True) == [
        "packed", ("host", 3), "packed", ("tail", 1)]
    # KITTI realtime: 312 -> L0 hosts, L2 (78, odd-block) stays plain.
    assert pack_plan(level_widths(312, 4), True) == [
        ("host", 3), "packed", "plain", ("tail", 0)]
    # fp32 never packs.
    assert pack_plan(level_widths(744, 4), False) == ["plain"] * 4


@pytest.mark.parametrize("w", [372, 373, 365, 744, 130])
def test_reg_tpu_combined_container_matches_reg(rng, w):
    """Widths whose plans pair two odd-block levels into ONE combined
    container (the L1-hosts-L3 layout at Middlebury-F): all four levels
    must match the fp32 reg path to bf16 rounding. Odd widths (373, 365)
    exercise the padding rule and the pooled-boundary artifact that the
    true-width mask must hide; 130 puts the host level at level 1 with a
    single-vreg standalone level 0."""
    from raft_stereo_tpu.corr.pallas_reg import level_widths, pack_plan
    plan = pack_plan(level_widths(w, LEVELS), True)
    assert any(isinstance(p, tuple) and p[0] == "host" for p in plan), plan
    b, h, d = 1, 3, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    ref = make_corr_fn("reg", f1, f2, num_levels=LEVELS, radius=RADIUS)(coords)
    out = make_corr_fn("reg_tpu", f1.astype(jnp.bfloat16),
                       f2.astype(jnp.bfloat16), num_levels=LEVELS,
                       radius=RADIUS)(coords)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=0.25, rtol=0.05)


def test_reg_tpu_combined_container_exact_vs_oracle(rng):
    """The combined host+tail container transports the SAME bf16 tap
    values as unpacked rows — bit-exact agreement per level against the
    masked one-hot oracle on the identical bf16 rows, isolating the
    tail-lane gather (static slab + lane offset) from volume rounding."""
    from raft_stereo_tpu.corr.pallas_reg import (
        _masked_lookup_xla, level_widths, make_reg_tpu_corr_fn, pack_plan,
        pad_width)
    from raft_stereo_tpu.corr.reg import build_pyramid
    b, h, w, d = 1, 3, 372, 16  # plan: [host(3), packed, plain, tail(0)]
    widths = level_widths(w, LEVELS)
    plan = pack_plan(widths, True)
    assert plan[0] == ("host", 3) and plan[3] == ("tail", 0), plan
    f1 = jnp.asarray(
        rng.standard_normal((b, h, w, d), dtype=np.float32)).astype(
            jnp.bfloat16)
    f2 = jnp.asarray(
        rng.standard_normal((b, h, w, d), dtype=np.float32)).astype(
            jnp.bfloat16)
    coords = jnp.asarray(
        rng.uniform(-8, w + 6, size=(b, h, w)).astype(np.float32))
    out = make_reg_tpu_corr_fn(f1, f2, num_levels=LEVELS,
                               radius=RADIUS)(coords)
    # Rebuild the identical bf16 rows the kernel saw and run the oracle.
    f2p = jnp.pad(f2, ((0, 0), (0, 0), (0, pad_width(w) - w), (0, 0)))
    vol = jnp.einsum("bhid,bhjd->bhij", f1, f2p) * (1.0 / d ** 0.5)
    rows = []
    for lvl, v in enumerate(build_pyramid(vol, LEVELS)):
        align = 256 if plan[lvl] == "packed" else 128
        want = -(-widths[lvl] // align) * align
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, want - v.shape[-1])))
        rows.append(v.reshape(b, h * w, -1))
    ref = _masked_lookup_xla(rows, coords.reshape(b, h * w, 1), RADIUS,
                             widths).reshape(b, h, w, -1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_reg_tpu_combined_container_grads_match_reg(rng):
    """Gradients through the combined-container lookup (zero cotangent on
    the container, all flow through the bf16 rows) track the reg path's,
    including from the tail level's output channels alone."""
    b, h, w, d = 1, 4, 372, 16
    k = 2 * RADIUS + 1
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(0, w, size=(b, h, w)).astype(np.float32))

    def loss(impl, f1_, f2_, sl):
        fn = make_corr_fn(impl, f1_.astype(jnp.bfloat16),
                          f2_.astype(jnp.bfloat16), num_levels=LEVELS,
                          radius=RADIUS)
        return jnp.sum(fn(coords).astype(jnp.float32)[..., sl] ** 2)

    for sl in (slice(3 * k, 4 * k), slice(None)):  # tail level alone; all
        g1, g2 = jax.grad(lambda a, c: loss("reg_tpu", a, c, sl),
                          argnums=(0, 1))(f1, f2)
        r1, r2 = jax.grad(lambda a, c: loss("reg", a, c, sl),
                          argnums=(0, 1))(f1, f2)
        for a_, b_ in ((g1, r1), (g2, r2)):
            a_, b_ = np.asarray(a_, np.float32), np.asarray(b_, np.float32)
            scale = np.abs(b_).max() + 1e-8
            assert np.abs(a_ - b_).max() / scale < 0.05, \
                np.abs(a_ - b_).max() / scale


def test_pack_unpack_rows_roundtrip(rng):
    """unpack_rows inverts pack_rows bit-exactly (the layout contract the
    packed kernel's in-register unpack relies on)."""
    from raft_stereo_tpu.corr.pallas_reg import pack_rows, unpack_rows
    rows = jnp.asarray(
        rng.standard_normal((2, 5, 256), dtype=np.float32)).astype(
            jnp.bfloat16)
    back = unpack_rows(pack_rows(rows))
    assert back.dtype == jnp.bfloat16 and back.shape == rows.shape
    assert (np.asarray(back, np.float32)
            == np.asarray(rows, np.float32)).all()


def test_reg_tpu_packed_deep_level_grads_flow(rng):
    """Gradients from pyramid levels >= 1 must reach the fmaps when level 0
    packs. Regression: deriving deeper levels through pack_rows' container
    (zero vjp + integer bitcasts) silently zeroed every deeper level's
    contribution — a loss reading ONLY deep-level channels had zero fmap
    grads. The grads must also track the reg path's (same bf16 volume)."""
    b, h, w, d = 1, 4, 200, 16  # w=200: level 0 packs (256 == 256)
    k = 2 * RADIUS + 1
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d), dtype=np.float32))
    coords = jnp.asarray(
        rng.uniform(0, w, size=(b, h, w)).astype(np.float32))

    def loss(impl, f1_, f2_):
        fn = make_corr_fn(impl, f1_.astype(jnp.bfloat16),
                          f2_.astype(jnp.bfloat16), num_levels=LEVELS,
                          radius=RADIUS)
        out = fn(coords).astype(jnp.float32)
        return jnp.sum(out[..., k:] ** 2)  # ONLY levels 1..3 channels

    g1, g2 = jax.grad(lambda a, c: loss("reg_tpu", a, c),
                      argnums=(0, 1))(f1, f2)
    assert np.abs(np.asarray(g2)).max() > 0, "deep-level grads dropped"
    r1, r2 = jax.grad(lambda a, c: loss("reg", a, c), argnums=(0, 1))(f1, f2)
    for a_, b_ in ((g1, r1), (g2, r2)):
        a_, b_ = np.asarray(a_, np.float32), np.asarray(b_, np.float32)
        scale = np.abs(b_).max() + 1e-8
        assert np.abs(a_ - b_).max() / scale < 0.05, \
            np.abs(a_ - b_).max() / scale


# ---------------------------------------------------------------------------
# r19: int8 quad-packed correlation containers (RAFT_CORR_PACK8).


def _pack8_case(rng, w=40, d=16, h=6, b=1):
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d)), jnp.bfloat16)
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d)), jnp.bfloat16)
    coords_x = jnp.asarray(
        rng.uniform(-4, w + 3, size=(b, h, w)).astype(np.float32))
    return f1, f2, coords_x


def test_pack8_error_budget_pinned(rng, monkeypatch):
    """The r19 quantization error budget, pinned: per level the int8
    lookup may differ from the exact bf16 lookup by at most ``scale/2``
    (symmetric scheme, scale = amax/127 — the DESIGN.md r19 budget; the
    lerp is a convex combination, so per-tap rounding error cannot
    amplify)."""
    from raft_stereo_tpu.corr.pallas_reg import (build_corr_operands,
                                                 corr_fn_from_operands)
    f1, f2, coords_x = _pack8_case(rng)
    ref = make_corr_fn("reg_tpu", f1, f2, num_levels=LEVELS, radius=RADIUS,
                       out_dtype=jnp.float32)(coords_x)
    monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    ops = build_corr_operands(f1, f2, num_levels=LEVELS, radius=RADIUS,
                              out_dtype=jnp.float32)
    assert ops["pack8"] and ops["scales"] is not None
    got = corr_fn_from_operands(ops)(coords_x)
    k = 2 * RADIUS + 1
    for lvl in range(LEVELS):
        # Per-SAMPLE scales (B, 1, 1): each sample's error is bounded by
        # its own scale/2; the per-sample max bound is exact.
        scale = np.asarray(ops["scales"][lvl]).reshape(-1)
        err = np.asarray(jnp.max(jnp.abs(
            got[..., lvl * k:(lvl + 1) * k]
            - ref[..., lvl * k:(lvl + 1) * k]), axis=(1, 2, 3)))
        assert (err <= 0.5 * scale * (1 + 1e-4)).all(), (lvl, err, scale)
    # Zero-pad semantics survive quantization exactly: far-out-of-range
    # coords produce EXACT zeros (symmetric scheme: q==0 <-> 0.0).
    far = jnp.full_like(coords_x, -1000.0)
    assert float(jnp.max(jnp.abs(
        corr_fn_from_operands(ops)(far)[..., :k]))) == 0.0


def test_pack8_plan_layout_and_dma_ratio():
    """pack_plan8's lane math: per-level segments at cumulative
    pad128(w)/4 bases, container padded to whole vregs; the headline
    int8/bf16 DMA ratio is the <= 0.6x acceptance number."""
    from raft_stereo_tpu.corr.pallas_reg import (level_widths, pack_plan8,
                                                 plan_dma_bytes)
    widths = level_widths(744, 4)  # Middlebury-F 1/4-res
    segs, total = pack_plan8(widths)
    assert segs == [(0, 192), (192, 96), (288, 64), (352, 32)]
    assert total == 384  # 3 whole slabs, zero pad bloat
    ratio = plan_dma_bytes(widths, True, True) \
        / plan_dma_bytes(widths, True, False)
    assert ratio <= 0.6, ratio


def test_pack8_gradients_identical_to_unpacked(rng, monkeypatch):
    """STE backward: the containers carry zero cotangent and the shared
    XLA-oracle backward reads the SAME bf16 rows — so fmap gradients are
    bitwise identical with pack8 on vs off."""
    f1, f2, coords_x = _pack8_case(rng)

    def loss(a, bm):
        fn = make_corr_fn("reg_tpu", a, bm, num_levels=LEVELS,
                          radius=RADIUS, out_dtype=jnp.float32)
        return jnp.sum(fn(coords_x))

    g_off = jax.grad(loss, argnums=(0, 1))(f1, f2)
    monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    g_on = jax.grad(loss, argnums=(0, 1))(f1, f2)
    for a, b_ in zip(g_off, g_on):
        assert np.asarray(a).tobytes() == np.asarray(b_).tobytes()


def test_pack8_default_off_and_fp32_inert(rng, monkeypatch):
    """Default env leaves the bf16 pair-pack plan untouched (bitwise),
    and fp32 volumes never pack regardless of the switch."""
    from raft_stereo_tpu.corr.pallas_reg import build_corr_operands
    f1, f2, coords_x = _pack8_case(rng)
    ops = build_corr_operands(f1, f2, num_levels=LEVELS, radius=RADIUS,
                              out_dtype=jnp.float32)
    assert not ops["pack8"]
    monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    f1_32 = f1.astype(jnp.float32)
    f2_32 = f2.astype(jnp.float32)
    ops32 = build_corr_operands(f1_32, f2_32, num_levels=LEVELS,
                                radius=RADIUS, out_dtype=jnp.float32)
    assert not ops32["pack8"] and ops32["scales"] is None


def test_pack8_odd_width_and_shallow_pyramid(rng, monkeypatch):
    """Budget pin at an odd width (non-128-multiple padding, straddling
    tap windows) and a 2-level pyramid — the pack plan must stay exact
    for every lane layout."""
    from raft_stereo_tpu.corr.pallas_reg import (build_corr_operands,
                                                 corr_fn_from_operands)
    f1, f2, coords_x = _pack8_case(rng, w=37, b=2)
    ref = make_corr_fn("reg_tpu", f1, f2, num_levels=2, radius=3,
                       out_dtype=jnp.float32)(coords_x)
    monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    ops = build_corr_operands(f1, f2, num_levels=2, radius=3,
                              out_dtype=jnp.float32)
    got = corr_fn_from_operands(ops)(coords_x)
    k = 7
    for lvl in range(2):
        scale = np.asarray(ops["scales"][lvl]).reshape(-1)
        err = np.asarray(jnp.max(jnp.abs(
            got[..., lvl * k:(lvl + 1) * k]
            - ref[..., lvl * k:(lvl + 1) * k]), axis=(1, 2, 3)))
        assert (err <= 0.5 * scale * (1 + 1e-4)).all(), (lvl, err, scale)


def test_pack8_batched_rows_independent(rng, monkeypatch):
    """Per-sample quantization scales: sample i's pack8 correlation must
    be BITWISE independent of its batchmates (a whole-batch amax would
    let one sample's content set another's quantization grid — breaking
    the batched-rows == B=1 invariant and the response cache's
    bit-identical-to-recompute contract; the review-round regression)."""
    from raft_stereo_tpu.corr.pallas_reg import (build_corr_operands,
                                                 corr_fn_from_operands)
    monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    f1, f2, coords_x = _pack8_case(rng, b=2)
    # Make sample 1 much higher-contrast so a batch-global amax would
    # provably change sample 0's grid.
    f1 = f1.at[1].multiply(17.0)
    f2 = f2.at[1].multiply(17.0)
    batched = corr_fn_from_operands(build_corr_operands(
        f1, f2, num_levels=LEVELS, radius=RADIUS,
        out_dtype=jnp.float32))(coords_x)
    for i in range(2):
        solo = corr_fn_from_operands(build_corr_operands(
            f1[i:i + 1], f2[i:i + 1], num_levels=LEVELS, radius=RADIUS,
            out_dtype=jnp.float32))(coords_x[i:i + 1])
        assert np.asarray(batched[i:i + 1]).tobytes() == \
            np.asarray(solo).tobytes(), f"row {i}"

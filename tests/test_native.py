"""Native (C++) photometric kernels: parity vs the numpy path.

The native path must be (a) available in this image (g++ is in the
toolchain), (b) deterministic, and (c) numerically equivalent to the numpy
implementation — same op order, same float32 per-pixel maths. The only
tolerated divergences are the contrast mean (double vs pairwise-float32
accumulation — a scalar ~1e-5 off) and the gamma LUT lerp, both bounded to
at most 1 uint8 count here.
"""

from unittest import mock

import numpy as np
import pytest

from raft_stereo_tpu import native
from raft_stereo_tpu.data import photometric
from raft_stereo_tpu.data.photometric import ColorJitter

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ compiler / native build failed")


def _img(rng, h=64, w=96):
    return rng.integers(0, 255, (h, w, 3), dtype=np.uint8)


REF_KW = dict(brightness=0.4, contrast=0.4, saturation=(0.6, 1.4),
              hue=0.5 / 3.14, gamma=(0.8, 1.2, 0.9, 1.1))


def _both_paths(img, seed, **kw):
    cj = ColorJitter(**kw)
    out_native = cj(img, np.random.default_rng(seed))
    with mock.patch.object(photometric.native, "lib", lambda: None):
        out_numpy = cj(img, np.random.default_rng(seed))
    return out_native, out_numpy


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_jitter_parity_with_numpy(rng, seed):
    img = _img(rng)
    a, b = _both_paths(img, seed, **REF_KW)
    assert a.shape == b.shape and a.dtype == b.dtype == np.uint8
    diff = np.abs(a.astype(np.int32) - b.astype(np.int32))
    assert diff.max() <= 1, f"native/numpy diverge by {diff.max()} counts"
    # knife-edge rounding may flip a pixel by 1 count, but only rarely
    assert (diff > 0).mean() < 0.01


def test_jitter_parity_no_hue_no_gamma(rng):
    img = _img(rng)
    a, b = _both_paths(img, 7, brightness=0.4, contrast=0.4,
                       saturation=(0.6, 1.4), hue=0.0)
    assert np.abs(a.astype(np.int32) - b.astype(np.int32)).max() <= 1


def test_native_deterministic(rng):
    img = _img(rng)
    cj = ColorJitter(**REF_KW)
    a = cj(img, np.random.default_rng(5))
    b = cj(img, np.random.default_rng(5))
    np.testing.assert_array_equal(a, b)


def test_native_kernels_match_ops_exactly(rng):
    """The per-op kernels vs their numpy counterparts on float32 buffers."""
    import ctypes
    lib = native.lib()
    f32p = ctypes.POINTER(ctypes.c_float)
    img = rng.uniform(0, 255, (48, 64, 3)).astype(np.float32)
    npix = img.shape[0] * img.shape[1]

    for name, ref_fn, factor in (
            ("rst_brightness", photometric.adjust_brightness, 1.3),
            ("rst_contrast", photometric.adjust_contrast, 0.7),
            ("rst_saturation", photometric.adjust_saturation, 1.2)):
        buf = np.ascontiguousarray(img.copy())
        getattr(lib, name)(buf.ctypes.data_as(f32p), npix, factor)
        np.testing.assert_allclose(buf, ref_fn(img, factor), atol=2e-3,
                                   err_msg=name)

    buf = np.ascontiguousarray(img.copy())
    lib.rst_gamma(buf.ctypes.data_as(f32p), npix, 1.3, 1.05)
    # LUT-lerp gamma: within a fraction of a count of the exact power curve
    np.testing.assert_allclose(buf, photometric.adjust_gamma(img, 1.3, 1.05),
                               atol=0.01)


def test_identity_factors_are_noops(rng):
    img = _img(rng)
    out = ColorJitter()(img, np.random.default_rng(0))
    np.testing.assert_array_equal(out, img)


def test_numpy_fallback_forced_by_env(rng, monkeypatch):
    """RAFT_NATIVE=0 must disable the native path (fresh module state)."""
    monkeypatch.setenv("RAFT_NATIVE", "0")
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_lib", None)
    assert native.lib() is None

"""Stub serve instance for the graftfleet tier-1 battery.

Speaks exactly the two surfaces the fleet supervisor consumes — the
``RAFT_HTTP_PORT=<n>`` stdout readiness handshake and the ``/healthz``
health-document schema — in milliseconds instead of the real
``serve_stereo.py``'s model-compile seconds, so the supervisor's whole
lifecycle (launch, probe, route, drain, replace, roll) is testable
inside the tier-1 budget.  Only the release gate
(``scratch/chaos_fleet.py``) pays for real instances.

Behaviors are argv-driven (the fleet's ``FleetConfig.command`` factory
builds per-slot/per-generation argv, so tests steer each launch):

    --fingerprint <id>       fingerprint_id reported on /healthz
    --headroom <rps>         capacity headroom_rps advertised
    --saturation <ratio>     capacity saturation ratio advertised
    --die-before-ready <f>   countdown file: while its integer is > 0,
                             decrement and exit(3) BEFORE the handshake
                             (the died-during-warmup satellite case —
                             the count survives relaunches)
    --ignore-term            mask SIGTERM (forces the supervisor's
                             SIGKILL drain escalation)
    --sick-after <n>         after n served requests, report the
                             scheduler heartbeat dead (the PR 9
                             watchdog surface of a hung instance)
    --warmup-s <s>           sleep before the handshake
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--fingerprint", default="stub-fp")
    parser.add_argument("--headroom", type=float, default=10.0)
    parser.add_argument("--saturation", type=float, default=0.0)
    parser.add_argument("--die-before-ready", default=None)
    parser.add_argument("--ignore-term", action="store_true")
    parser.add_argument("--sick-after", type=int, default=None)
    parser.add_argument("--warmup-s", type=float, default=0.0)
    args = parser.parse_args()

    if args.die_before_ready:
        try:
            with open(args.die_before_ready) as f:
                remaining = int(f.read().strip() or "0")
        except OSError:
            remaining = 0
        if remaining > 0:
            with open(args.die_before_ready, "w") as f:
                f.write(str(remaining - 1))
            return 3

    if args.ignore_term:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)

    born = time.monotonic()
    state = {"ok": 0}
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):  # noqa: A003 — stdlib signature
            pass

        def _send(self, status, doc):
            body = json.dumps(doc).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — stdlib handler naming
            if self.path.split("?", 1)[0] != "/healthz":
                return self._send(404, {"status": "rejected",
                                        "code": "not_found"})
            with lock:
                served = state["ok"]
            sick = (args.sick_after is not None
                    and served >= args.sick_after)
            self._send(200, {
                "fingerprint_id": args.fingerprint,
                "uptime_s": time.monotonic() - born,
                "requests": {"ok": served},
                "stream": {"sessions": 0},
                "cache": {"entries": 0},
                "supervision": {"heartbeats": {
                    "scheduler_alive": not sick,
                    "scheduler_died": ("stub sick" if sick else None),
                }},
                "capacity": {
                    "by_bucket": {"64x64": {
                        "headroom_rps": args.headroom}},
                    "saturation": {"ratio": args.saturation},
                },
            })

        def do_POST(self):  # noqa: N802 — stdlib handler naming
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if self.path.split("?", 1)[0] != "/v1/stereo":
                return self._send(404, {"status": "rejected",
                                        "code": "not_found"})
            with lock:
                state["ok"] += 1
            self._send(200, {
                "status": "ok",
                "fingerprint_id": args.fingerprint,
                "session": self.headers.get("X-Raft-Session"),
                "bytes_in": len(body),
            })

    class Server(ThreadingHTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    server = Server(("127.0.0.1", 0), Handler)
    if args.warmup_s > 0:
        time.sleep(args.warmup_s)
    print(f"RAFT_HTTP_PORT={server.server_address[1]}", flush=True)
    try:
        server.serve_forever(poll_interval=0.05)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Driver-contract insurance: the harness's entry points must keep working.

The driver (a) compile-checks ``__graft_entry__.entry()``, (b) runs
``bench.py`` expecting ONE JSON line with metric/value/unit/vs_baseline, and
(c) runs ``dryrun_multichip``. A regression in any of these surfaces only at
round end otherwise. These run the real scripts in subprocesses on CPU at
tiny shapes (the dryrun path is covered by the driver itself and by
``python -c "import __graft_entry__; ..."`` in the verify skill).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = str(Path(__file__).resolve().parent.parent)


def _cpu_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single-device CPU is fine here
    env.update(extra)
    return env


def test_bench_prints_one_json_line_with_contract_keys():
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "import bench; bench.main()"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env=_cpu_env(RAFT_BENCH_H="64", RAFT_BENCH_W="128",
                     RAFT_BENCH_ITERS="2", RAFT_BENCH_FRAMES="1",
                     RAFT_BENCH_CORR="reg_tpu"))
    assert r.returncode == 0, r.stderr[-800:]
    json_lines = [l for l in r.stdout.splitlines() if l.startswith("{")]
    assert len(json_lines) == 1, r.stdout
    rec = json.loads(json_lines[0])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "frames/s" and rec["value"] > 0


def test_entry_compiles_and_runs():
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "import __graft_entry__ as g; "
         "fn, args = g.entry(); out = jax.jit(fn)(*args); "
         "print('shape', out.shape, out.dtype)"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env=_cpu_env())
    assert r.returncode == 0, r.stderr[-800:]
    assert "shape (1, 64, 128, 1) float32" in r.stdout

"""Poisoned registry: a hot-path program with a ``jax.debug.print`` left
in the scan body — a device->host round trip per iteration. GV103 must
fire."""

from raft_stereo_tpu.analysis.trace.registry import TraceEntry, TraceRegistry


def build_registry():
    def build():
        import jax
        import jax.numpy as jnp
        from jax import lax

        def fn(x):
            def step(h, _):
                jax.debug.print("h sum = {}", h.sum())
                return h * 1.5, None
            h, _ = lax.scan(step, x, None, length=2)
            return h
        return fn, (jax.ShapeDtypeStruct((8, 8), jnp.float32),)

    entry = TraceEntry(name="fixture/debug_print", build=build, env={},
                       hot_path="serve")
    return TraceRegistry(geometry="fixture", entries=[entry],
                         ladder_variants=[], knob_flips=[])

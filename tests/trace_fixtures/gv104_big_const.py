"""Poisoned registry: a closure-captured 4 MiB concrete array baked into
the program as a jaxpr constant (the "oversized closure constant" class —
should have been an argument). GV104 must fire at the default 2 MiB
threshold."""

from raft_stereo_tpu.analysis.trace.registry import TraceEntry, TraceRegistry


def build_registry():
    def build():
        import jax
        import jax.numpy as jnp
        import numpy as np

        baked = np.ones((1024, 1024), np.float32)  # 4 MiB closure capture

        def fn(x):
            return x + jnp.asarray(baked)
        return fn, (jax.ShapeDtypeStruct((1024, 1024), jnp.float32),)

    entry = TraceEntry(name="fixture/big_const", build=build, env={},
                       hot_path="serve")
    return TraceRegistry(geometry="fixture", entries=[entry],
                         ladder_variants=[], knob_flips=[])

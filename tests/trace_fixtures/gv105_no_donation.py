"""Poisoned registry: a train-step-shaped program whose jit lost its
``donate_argnums`` — the lowered module aliases nothing, peak HBM holds
params twice. GV105 must fire on every non-scalar donated leaf."""

from raft_stereo_tpu.analysis.trace.registry import TraceEntry, TraceRegistry


def _pieces():
    import jax
    import jax.numpy as jnp

    def step(params, batch):
        new = jax.tree_util.tree_map(lambda a: a * 0.99, params)
        return new, batch.sum()

    pspec = {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32),
             "b": jax.ShapeDtypeStruct((64,), jnp.float32)}
    bspec = jax.ShapeDtypeStruct((8, 64), jnp.float32)
    # The poison: donate_argnums deleted — jax.jit(step) instead of
    # jax.jit(step, donate_argnums=(0,)).
    return jax.jit(step), pspec, bspec


def build_registry():
    def build():
        step, pspec, bspec = _pieces()
        return step, (pspec, bspec)

    def build_lowered():
        import jax
        step, pspec, bspec = _pieces()
        leaves = jax.tree_util.tree_flatten_with_path((pspec,))[0]
        return (step.lower(pspec, bspec).as_text(),
                [(jax.tree_util.keystr(p), v) for p, v in leaves])

    entry = TraceEntry(name="fixture/train_no_donate", build=build, env={},
                       hot_path="train", build_lowered=build_lowered)
    return TraceRegistry(geometry="fixture", entries=[entry],
                         ladder_variants=[], knob_flips=[])

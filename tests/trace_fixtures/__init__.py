# Poisoned trace-registry fixtures for the graftverify vacuity guards:
# each <code>_*.py defines build_registry() returning a registry on which
# exactly that GV checker must fire (tests/test_trace_analysis.py drives
# them through the real CLI via --trace-registry).

"""Poisoned registry: a breaker rung whose "fallback" is the identical
program (its switch is consulted nowhere), plus a knob flip that changes
the program but NOT the cache key — the stale-program class. GV102 must
fire twice."""

from raft_stereo_tpu.analysis.trace.registry import (KnobFlip, TraceEntry,
                                                     TraceRegistry)


def _entry(name, mult):
    def build():
        import jax
        import jax.numpy as jnp

        def fn(x):
            return x * mult
        return fn, (jax.ShapeDtypeStruct((8, 8), jnp.float32),)
    return TraceEntry(name=name, build=build, env={}, hot_path="serve")


def build_registry():
    base = _entry("fixture/base", 2.0)
    noop_rung = _entry("fixture/noop_rung", 2.0)   # identical program
    flipped = _entry("fixture/flipped", 3.0)       # different program...
    stale = KnobFlip(knob="RAFT_FIXTURE_KNOB", flip_value="0",
                     base=base, flipped=flipped,
                     base_key=("same",), flipped_key=("same",))  # ...same key
    return TraceRegistry(
        geometry="fixture", entries=[base],
        ladder_variants=[("untripped", base), ("noop_rung", noop_rung)],
        knob_flips=[stale])

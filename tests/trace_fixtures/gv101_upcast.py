"""Poisoned registry: a scan body silently upcasts its bf16 hidden state
to fp32, computes, and downcasts back — the exact shape of "this layer
quietly runs in fp32 every iteration". GV101 must fire: the upcast
neither reaches an fp32 carry nor feeds a reduction."""

from raft_stereo_tpu.analysis.trace.registry import TraceEntry, TraceRegistry


def build_registry():
    def build():
        import jax
        import jax.numpy as jnp
        from jax import lax

        def fn(x):
            def step(h, _):
                h32 = h.astype(jnp.float32)      # the poisoned upcast
                h = (h32 * 1.5).astype(jnp.bfloat16)
                return h, None
            h, _ = lax.scan(step, x, None, length=4)
            return h
        return fn, (jax.ShapeDtypeStruct((64, 64, 16), jnp.bfloat16),)

    entry = TraceEntry(name="fixture/upcast", build=build, env={},
                       hot_path="serve", mixed_precision=True)
    return TraceRegistry(geometry="fixture", entries=[entry],
                         ladder_variants=[], knob_flips=[])

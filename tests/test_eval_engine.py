"""Eval engine tests: metric aggregation quirks, checkpointing, logger, train loop.

The four validators are tested with a stubbed forward (zero predictions ->
EPE equals |gt| exactly), pinning each benchmark's aggregation quirk without
paying model compiles. One real end-to-end train-loop smoke runs the full
stack at tiny shapes.
"""

import os
import os.path as osp

import numpy as np
import pytest
from PIL import Image

import cv2

import jax

from raft_stereo_tpu.config import RAFTStereoConfig, TrainConfig
from raft_stereo_tpu.data import frame_utils
from raft_stereo_tpu.engine import checkpoint as ckpt
from raft_stereo_tpu.engine import evaluate as ev
from raft_stereo_tpu.engine.logger import SUM_FREQ, Logger
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.models import init_raft_stereo

TINY = RAFTStereoConfig(hidden_dims=(32, 32, 32), corr_levels=2, corr_radius=2)


def _zero_forward(params, cfg, iters, mixed_prec=False, mesh=None,
                  segments=1):
    def forward(image1, image2):
        return np.zeros(image1.shape[:3] + (1,), np.float32), 0.01
    return forward


def _write_png(path, arr):
    os.makedirs(osp.dirname(str(path)), exist_ok=True)
    Image.fromarray(arr).save(path)


# ---------------------------------------------------------------------------
# Validators with stubbed forward: aggregation quirks
# ---------------------------------------------------------------------------

def _make_eth3d_tree(root, disps):
    """One scene per disp value; disparity is constant over a 40x64 image."""
    img = np.zeros((40, 64, 3), np.uint8)
    for i, d in enumerate(disps):
        scene = f"scene_{i}"
        _write_png(osp.join(root, "two_view_training", scene, "im0.png"), img)
        _write_png(osp.join(root, "two_view_training", scene, "im1.png"), img)
        gt_dir = osp.join(root, "two_view_training_gt", scene)
        os.makedirs(gt_dir, exist_ok=True)
        frame_utils.write_pfm(osp.join(gt_dir, "disp0GT.pfm"),
                              np.full((40, 64), d, np.float32))


def test_prefetch_samples_matches_direct_indexing(tmp_path):
    """The validators' decode/compute overlap must not change sample order
    or contents vs plain ``dataset[i]`` iteration."""
    from raft_stereo_tpu.data import datasets as ds
    _make_eth3d_tree(str(tmp_path / "ETH3D"), [0.5, 2.0, 3.0])
    dataset = ds.ETH3D(aug_params=None, root=str(tmp_path / "ETH3D"))
    direct = [dataset[i] for i in range(len(dataset))]
    fetched = list(ev.prefetch_samples(dataset))
    assert len(fetched) == len(direct) == 3
    for a, b in zip(fetched, direct):
        assert a["paths"] == b["paths"]
        np.testing.assert_array_equal(a["image1"], b["image1"])
        np.testing.assert_array_equal(a["flow"], b["flow"])
    assert list(ev.prefetch_samples(dataset * 0)) == []  # empty dataset


def test_validate_eth3d_per_image_aggregation(tmp_path, monkeypatch):
    monkeypatch.setattr(ev, "make_eval_forward", _zero_forward)
    # Two images, disparities 0.5 (inlier at >1px) and 2.0 (outlier).
    _make_eth3d_tree(str(tmp_path / "ETH3D"), [0.5, 2.0])
    res = ev.validate_eth3d(None, TINY, iters=2, root=str(tmp_path))
    np.testing.assert_allclose(res["eth3d-epe"], (0.5 + 2.0) / 2)
    np.testing.assert_allclose(res["eth3d-d1"], 50.0)  # per-image mean


def test_validate_middlebury_sentinel_filter(tmp_path, monkeypatch):
    monkeypatch.setattr(ev, "make_eval_forward", _zero_forward)
    root = str(tmp_path / "Middlebury")
    img = np.zeros((40, 64, 3), np.uint8)
    scene = "artroom1"
    base = osp.join(root, "MiddEval3", "trainingF", scene)
    _write_png(osp.join(base, "im0.png"), img)
    _write_png(osp.join(base, "im1.png"), img)
    disp = np.full((40, 64), 1.0, np.float32)
    disp[:20] = np.inf  # invalid region -> flow=-inf, filtered by > -1000
    frame_utils.write_pfm(osp.join(base, "disp0GT.pfm"), disp)
    mask = np.full((40, 64), 255, np.uint8)
    mask[:, :32] = 128  # nocc mask is IGNORED by the reference metric
    _write_png(osp.join(base, "mask0nocc.png"), mask)
    with open(osp.join(root, "MiddEval3", "official_train.txt"), "w") as f:
        f.write(f"{scene}\n")

    res = ev.validate_middlebury(None, TINY, iters=2, split="F",
                                 root=str(tmp_path))
    # Only the inf rows are filtered; the nocc mask does not reduce the count.
    np.testing.assert_allclose(res["middleburyF-epe"], 1.0)
    np.testing.assert_allclose(res["middleburyF-d1"], 0.0)


def test_validate_kitti_per_pixel_aggregation(tmp_path, monkeypatch):
    monkeypatch.setattr(ev, "make_eval_forward", _zero_forward)
    root = str(tmp_path / "KITTI")
    img = np.zeros((40, 64, 3), np.uint8)
    # Image 0: 100 valid px at disp 5 (outliers at >3px);
    # image 1: 300 valid px at disp 1 (inliers).
    for i, (n_valid, d) in enumerate([(100, 5.0), (300, 1.0)]):
        _write_png(osp.join(root, "training", "image_2", f"{i:06d}_10.png"), img)
        _write_png(osp.join(root, "training", "image_3", f"{i:06d}_10.png"), img)
        disp = np.zeros((40, 64), np.float32)
        disp.flat[:n_valid] = d
        os.makedirs(osp.join(root, "training", "disp_occ_0"), exist_ok=True)
        cv2.imwrite(osp.join(root, "training", "disp_occ_0", f"{i:06d}_10.png"),
                    (disp * 256).astype(np.uint16))
    res = ev.validate_kitti(None, TINY, iters=2, root=str(tmp_path))
    # Per-pixel: 100 outliers / 400 valid = 25% (per-image would be 50%).
    np.testing.assert_allclose(res["kitti-d1"], 25.0)
    np.testing.assert_allclose(res["kitti-epe"], (5.0 + 1.0) / 2)


def test_validate_things_192_filter(tmp_path, monkeypatch):
    monkeypatch.setattr(ev, "make_eval_forward", _zero_forward)
    root = str(tmp_path)
    img = np.zeros((40, 64, 3), np.uint8)
    base = osp.join(root, "FlyingThings3D")
    _write_png(osp.join(base, "frames_finalpass", "TEST", "A", "0000",
                        "left", "0006.png"), img)
    _write_png(osp.join(base, "frames_finalpass", "TEST", "A", "0000",
                        "right", "0006.png"), img)
    disp = np.full((40, 64), 2.0, np.float32)
    disp[0, :10] = 400.0  # beyond the 192 magnitude filter
    ddir = osp.join(base, "disparity", "TEST", "A", "0000", "left")
    os.makedirs(ddir, exist_ok=True)
    frame_utils.write_pfm(osp.join(ddir, "0006.pfm"), disp)
    res = ev.validate_things(None, TINY, iters=2, root=root)
    np.testing.assert_allclose(res["things-epe"], 2.0)  # 400s filtered out
    np.testing.assert_allclose(res["things-d1"], 100.0)  # all >1px


# ---------------------------------------------------------------------------
# Checkpointing
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip(tmp_path):
    cfg = TINY
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    tx, _ = make_optimizer(1e-4, 100)
    opt_state = tx.init(params)
    path = str(tmp_path / "ck.msgpack")
    ckpt.save_checkpoint(path, params, opt_state, step=17)

    params2 = init_raft_stereo(jax.random.PRNGKey(1), cfg)
    opt2 = tx.init(params2)
    rp, ro, step = ckpt.load_checkpoint(path, params2, opt2)
    assert step == 17
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(rp)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert len(jax.tree.leaves(ro)) == len(jax.tree.leaves(opt_state))


def test_load_params_dispatches_native(tmp_path):
    cfg = TINY
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / "p.msgpack")
    ckpt.save_checkpoint(path, params)
    out = ckpt.load_params(path, cfg, params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_count_parameters():
    params = {"a": np.zeros((2, 3)), "b": [np.zeros(5), np.zeros((1, 1))]}
    assert ev.count_parameters(params) == 6 + 5 + 1


# ---------------------------------------------------------------------------
# Logger
# ---------------------------------------------------------------------------

def test_logger_running_mean_flush(tmp_path):
    log = Logger(log_dir=str(tmp_path / "runs"))
    # Flush fires on the push where total_steps % SUM_FREQ == SUM_FREQ-1
    # (reference Logger.push, train_stereo.py:108-118).
    for _ in range(SUM_FREQ - 1):
        log.push({"loss": 2.0})
    assert log.running_loss == {}  # flushed on push SUM_FREQ-1
    log.push({"loss": 2.0})
    assert log.running_loss == {"loss": 2.0}  # accumulation restarted
    log.write_dict({"things-epe": 1.5})
    log.close()
    assert any(os.scandir(tmp_path / "runs"))  # event file written


# ---------------------------------------------------------------------------
# Train loop smoke (real model, tiny shapes)
# ---------------------------------------------------------------------------

def _tiny_things_tree(tmp_path) -> str:
    root = str(tmp_path / "data")
    rng = np.random.default_rng(0)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        base = osp.join(root, "FlyingThings3D", dstype, "TRAIN", "A", "0000")
        for side in ("left", "right"):
            _write_png(osp.join(base, side, "0006.png"),
                       rng.integers(0, 256, (48, 64, 3), dtype=np.uint8))
    ddir = osp.join(root, "FlyingThings3D", "disparity", "TRAIN", "A", "0000",
                    "left")
    os.makedirs(ddir, exist_ok=True)
    frame_utils.write_pfm(osp.join(ddir, "0006.pfm"),
                          rng.uniform(1, 10, (48, 64)).astype(np.float32))
    return root


@pytest.mark.slow
def test_train_loop_checkpoints_and_resume(tmp_path, monkeypatch):
    from raft_stereo_tpu.engine.train import train

    root = _tiny_things_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    cfg = TINY
    tcfg = TrainConfig(name="smoke", batch_size=1, image_size=(32, 48),
                       num_steps=3, train_iters=2, ckpt_every=2,
                       num_workers=1, spatial_scale=(-0.2, 0.4))
    train(cfg, tcfg, data_root=root, validate=False)
    assert osp.exists("checkpoints/2_smoke.msgpack")
    assert osp.exists("checkpoints/smoke.msgpack")

    # Resume from the mid-run checkpoint: picks up at step 2.
    tcfg2 = TrainConfig(name="smoke2", batch_size=1, image_size=(32, 48),
                        num_steps=4, train_iters=2, ckpt_every=100,
                        num_workers=1, restore_ckpt="checkpoints/2_smoke.msgpack",
                        spatial_scale=(-0.2, 0.4))
    train(cfg, tcfg2, data_root=root, validate=False)
    _, _, step = ckpt.load_checkpoint(
        "checkpoints/smoke2.msgpack",
        init_raft_stereo(jax.random.PRNGKey(0), cfg),
        None)
    assert step == 4


def test_preempt_guard_catches_sigterm():
    import signal
    import time

    from raft_stereo_tpu.engine.train import PreemptGuard

    guard = PreemptGuard()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.01)  # handler runs at the next bytecode boundary
        assert guard.requested
        assert guard.stop()  # single-process: no collective involved
    finally:
        guard.restore()


@pytest.mark.slow
def test_train_preemption_checkpoint_and_trace(tmp_path, monkeypatch):
    """SIGTERM-equivalent stop mid-run: a preempt checkpoint with the step
    count appears and the loop exits cleanly; --trace_dir captures a
    steady-state step profile."""
    from raft_stereo_tpu.engine import train as train_mod

    root = _tiny_things_tree(tmp_path)
    monkeypatch.chdir(tmp_path)

    calls = {"n": 0}

    def fake_stop(self, step=0):
        calls["n"] += 1
        return calls["n"] >= 4 or self.requested

    monkeypatch.setattr(train_mod.PreemptGuard, "stop", fake_stop)
    tcfg = TrainConfig(name="pre", batch_size=1, image_size=(32, 48),
                       num_steps=50, train_iters=2, ckpt_every=100,
                       num_workers=1, spatial_scale=(-0.2, 0.4),
                       trace_dir=str(tmp_path / "trace"))
    train_mod.train(TINY, tcfg, data_root=root, validate=False)

    assert osp.exists("checkpoints/4_preempt_pre.msgpack")
    # a preempted run must not masquerade as a finished one
    assert not osp.exists("checkpoints/pre.msgpack")
    _, _, step = ckpt.load_checkpoint(
        "checkpoints/4_preempt_pre.msgpack",
        init_raft_stereo(jax.random.PRNGKey(0), TINY), None)
    assert step == 4  # resume continues the schedule from here
    trace_files = [f for _, _, fs in os.walk(tmp_path / "trace") for f in fs]
    assert trace_files, "profiler trace was not written"


def test_make_eval_forward_spatial_mesh_matches(rng):
    """H-sharded eval forward (the --spatial_shard path) equals unsharded."""
    from raft_stereo_tpu.engine.evaluate import make_eval_forward
    from raft_stereo_tpu.models import init_raft_stereo
    from raft_stereo_tpu.parallel import make_mesh

    cfg = RAFTStereoConfig(n_gru_layers=1)
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1 = rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32)

    plain = make_eval_forward(params, cfg, iters=2)
    mesh = make_mesh(n_data=1, n_space=8)
    sharded = make_eval_forward(params, cfg, iters=2, mesh=mesh)
    out_p, _ = plain(img1, img2)
    out_s, _ = sharded(img1, img2)
    np.testing.assert_allclose(out_s, out_p, atol=2e-3)


@pytest.mark.slow
def test_train_cli_end_to_end(tmp_path, monkeypatch):
    """train_stereo.py argparse -> config -> engine wiring, 2 steps."""
    import train_stereo

    root = str(tmp_path / "data")
    rng = np.random.default_rng(1)
    for dstype in ("frames_cleanpass", "frames_finalpass"):
        base = osp.join(root, "FlyingThings3D", dstype, "TRAIN", "A", "0000")
        for side in ("left", "right"):
            _write_png(osp.join(base, side, "0006.png"),
                       rng.integers(0, 256, (48, 64, 3), dtype=np.uint8))
    ddir = osp.join(root, "FlyingThings3D", "disparity", "TRAIN", "A", "0000",
                    "left")
    os.makedirs(ddir, exist_ok=True)
    frame_utils.write_pfm(osp.join(ddir, "0006.pfm"),
                          rng.uniform(1, 10, (48, 64)).astype(np.float32))

    monkeypatch.chdir(tmp_path)
    train_stereo.main([
        "--name", "clismoke", "--batch_size", "1", "--num_steps", "2",
        "--train_iters", "2", "--image_size", "32", "48",
        "--hidden_dims", "32", "32", "32", "--corr_levels", "2",
        "--corr_radius", "2", "--num_workers", "1",
        "--dataset_root", root])
    assert osp.exists("checkpoints/clismoke.msgpack")

"""graftwire battery: the hardened HTTP ingress (serve/http.py) and its
wire codec (serve/wire.py), proven against hostile clients over REAL
loopback sockets — the server side is unmodified production code.

Three layers, mirroring the module split:

- codec units: the strict multipart parser, the raw-pair framing, the
  response-contract round-trip and the honest status mapping — pure
  bytes-in/values-out, no server;
- the decompression-bomb guard: a crafted huge-header PNG (a few hundred
  file bytes declaring 400 MP) is rejected from the HEADER alone, both
  at the file path (``read_image_rgb``) and the wire decode;
- the live battery: a tiny CPU service behind a real listener — the
  malformed-request storm pins ONE stable structured code per case and
  that the acceptor survives every one of them; loopback parity pins
  byte-identical disparity vs in-process ``submit``; per-tenant quota
  rejections are exact; drain answers 503 ``service_draining``.

Everything runs on CPU with the tiny model config; the only real time
spent is the stalled-client test's deliberately short read timeout.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.data.frame_utils import (ImageTooLarge, read_image_rgb,
                                              resolve_decode_max_pixels)
from raft_stereo_tpu.faults import WIRE_FAULT_KINDS, WireChaosPlan, bomb_png
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.serve import (HttpConfig, HttpFrontend, InferenceSession,
                                   ServiceConfig, SessionConfig,
                                   StereoService)
from raft_stereo_tpu.serve import wire
from raft_stereo_tpu.serve.http import (TenantQuotas, _TokenBucket,
                                        resolve_body_max,
                                        resolve_read_timeout_ms,
                                        resolve_tenant_rate, sanitize_tenant)

pytestmark = pytest.mark.http

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60


def png_pair(h=H, w=W, seed=0):
    rng = np.random.default_rng(seed)
    left = rng.uniform(0, 255, (h, w, 3)).astype(np.uint8)
    right = rng.uniform(0, 255, (h, w, 3)).astype(np.uint8)
    return left, right


# ---------------------------------------------------------------------------
# Codec units (no server)
# ---------------------------------------------------------------------------


def test_multipart_roundtrip():
    ct, body = wire.build_multipart({"left": b"L" * 100, "right": b"R" * 7,
                                     "id": b"x-1"})
    media, params = wire.parse_content_type(ct)
    assert media == "multipart/form-data"
    parts = wire.parse_multipart(body, params["boundary"])
    assert parts == {"left": b"L" * 100, "right": b"R" * 7, "id": b"x-1"}


@pytest.mark.parametrize("mangle", [
    lambda b: b[:len(b) // 2],            # truncated mid-part
    lambda b: b[:-6],                     # closing terminator cut
    lambda b: b"junk" + b,                # does not open with boundary
    lambda b: b.replace(b"--raftwire\r\n", b"--raftwire..", 1),
    #                                     ^ delimiter without its CRLF
])
def test_multipart_strict_rejects(mangle):
    _, body = wire.build_multipart({"left": b"LL", "right": b"RR"})
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_multipart(mangle(body), "raftwire")
    assert exc.value.code == "bad_multipart"


def test_multipart_no_boundary_param():
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_stereo_request("multipart/form-data", {}, b"--x\r\n")
    assert exc.value.code == "bad_multipart"


def test_raw_pair_framing():
    body = b"LEFTBYTES" + b"RIGHT"
    headers = {"X-Raft-Left-Len": "9", "X-Raft-Right-Len": "5",
               "X-Raft-Id": "r-0", "X-Raft-Deadline-Ms": "1500"}
    req = wire.parse_stereo_request(
        "application/x-raft-stereo", headers, body)
    assert req["left"] == b"LEFTBYTES" and req["right"] == b"RIGHT"
    assert req["id"] == "r-0" and req["deadline_ms"] == 1500.0


@pytest.mark.parametrize("headers,code", [
    ({}, "missing_part"),
    ({"X-Raft-Left-Len": "nine", "X-Raft-Right-Len": "5"},
     "bad_part_lengths"),
    ({"X-Raft-Left-Len": "-1", "X-Raft-Right-Len": "15"},
     "bad_part_lengths"),
    ({"X-Raft-Left-Len": "9", "X-Raft-Right-Len": "99"},
     "bad_part_lengths"),  # declared split != body (truncated upload)
])
def test_raw_pair_bad_framing(headers, code):
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_stereo_request("application/x-raft-stereo", headers,
                                  b"LEFTBYTESRIGHT")
    assert exc.value.code == code


def test_unsupported_media_type_and_empty_body():
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_stereo_request("text/plain", {}, b"hello")
    assert exc.value.code == "unsupported_media_type"
    assert exc.value.http_status == 415
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_stereo_request("multipart/form-data", {}, b"")
    assert exc.value.code == "empty_body"


@pytest.mark.parametrize("raw", [b"soon", b"nan", b"inf", b"-inf"])
def test_bad_deadline_rejected(raw):
    # float() accepts "nan"/"inf" — a NaN deadline silently disables the
    # deadline machinery (every now-vs-deadline comparison is False), so
    # non-finite values are bad_deadline like any other garbage.
    ct, body = wire.build_multipart({"left": b"L", "right": b"R",
                                     "deadline_ms": raw})
    with pytest.raises(wire.WireRejected) as exc:
        wire.parse_stereo_request(ct, {}, body)
    assert exc.value.code == "bad_deadline"


def test_response_contract_survives_the_wire():
    """The PR 3 response contract — quality labels, structured errors,
    ``retries: k`` — serializes unchanged, disparity bit-exact."""
    disp = np.linspace(-3, 7, 24, dtype=np.float32).reshape(1, 4, 6)
    resp = {"status": "ok", "id": "q-7", "quality": "reduced_iters:16",
            "retries": 2, "elapsed_ms": 12.5, "disparity": disp}
    back = wire.decode_response(wire.encode_response(resp))
    assert back["status"] == "ok" and back["id"] == "q-7"
    assert back["quality"] == "reduced_iters:16" and back["retries"] == 2
    assert back["disparity"].dtype == np.float32
    assert back["disparity"].tobytes() == disp.tobytes()

    rej = {"status": "rejected", "code": "queue_full", "message": "full"}
    assert wire.decode_response(wire.encode_response(rej)) == rej


@pytest.mark.parametrize("resp,status,retry_after", [
    ({"status": "ok"}, 200, None),
    ({"status": "error", "code": "nonfinite_output"}, 500, None),
    ({"status": "rejected", "code": "queue_full"}, 503, 1),
    ({"status": "rejected", "code": "service_draining"}, 503, 5),
    ({"status": "rejected", "code": "quota_exceeded"}, 429, 1),
    ({"status": "rejected", "code": "deadline_exceeded"}, 504, None),
    ({"status": "rejected", "code": "invalid_input:too_large"}, 400, None),
])
def test_status_mapping(resp, status, retry_after):
    assert wire.http_status_for(resp) == status
    assert wire.retry_after_for(resp) == retry_after


def test_decode_image_garbage_and_bomb():
    with pytest.raises(wire.WireRejected) as exc:
        wire.decode_image_rgb(b"\x89PNG but not really", "left")
    assert exc.value.code == "bad_image" and exc.value.http_status == 400
    # 64 MP: above OUR cap (32 MP default), below PIL's own tripwire —
    # the registered-knob guard is what fires
    with pytest.raises(wire.WireRejected) as exc:
        wire.decode_image_rgb(bomb_png(8_000, 8_000), "left")
    assert exc.value.code == "image_too_large"
    assert exc.value.http_status == 413
    assert "8000x8000" in str(exc.value)
    # 400 MP: lands in PIL's DecompressionBombError inside open() —
    # folded into the SAME stable code, not a second error contract
    with pytest.raises(wire.WireRejected) as exc:
        wire.decode_image_rgb(bomb_png(20_000, 20_000), "left")
    assert exc.value.code == "image_too_large"
    assert exc.value.http_status == 413


def test_wire_chaos_plan_seeded_deterministic():
    a = WireChaosPlan.seeded(7, 64)
    b = WireChaosPlan.seeded(7, 64)
    assert a.faults == b.faults
    # Every hostile kind appears before any repeats — a small storm still
    # exercises the full fault surface.
    kinds = set(a.faults.values())
    assert kinds == set(k for k in WIRE_FAULT_KINDS if k != "ok")
    assert WireChaosPlan.seeded(8, 64).faults != a.faults


# ---------------------------------------------------------------------------
# Decompression-bomb guard at the file path
# ---------------------------------------------------------------------------


def test_read_image_rgb_bomb_guard(tmp_path):
    """Regression (satellite 1): a crafted PNG declaring 400 MP from a
    few hundred file bytes must die on the header, stable code
    ``image_too_large`` — never a ~1.2 GB allocation."""
    for side in (8_000, 20_000):  # our guard / PIL's own tripwire
        p = tmp_path / f"bomb{side}.png"
        p.write_bytes(bomb_png(side, side))
        assert p.stat().st_size < 1024  # the whole point: tiny file
        with pytest.raises(ImageTooLarge) as exc:
            read_image_rgb(p)
        assert exc.value.code == "image_too_large"


def test_read_image_rgb_legit_passes(tmp_path):
    left, _ = png_pair(8, 12)
    p = tmp_path / "ok.png"
    p.write_bytes(wire.encode_image_png(left))
    assert np.array_equal(read_image_rgb(p), left)


def test_resolve_decode_max_pixels(monkeypatch):
    assert resolve_decode_max_pixels(123) == 123
    monkeypatch.setenv("RAFT_DECODE_MAX_PIXELS", "4096")
    assert resolve_decode_max_pixels() == 4096
    monkeypatch.setenv("RAFT_DECODE_MAX_PIXELS", "many")
    with pytest.raises(ValueError, match="RAFT_DECODE_MAX_PIXELS"):
        resolve_decode_max_pixels()


# ---------------------------------------------------------------------------
# Knob resolvers + tenant quota state (no server)
# ---------------------------------------------------------------------------


def test_http_knob_resolvers_named_errors(monkeypatch):
    monkeypatch.setenv("RAFT_HTTP_BODY_MAX", "1048576")
    assert resolve_body_max() == 1 << 20
    monkeypatch.setenv("RAFT_HTTP_BODY_MAX", "big")
    with pytest.raises(ValueError, match="RAFT_HTTP_BODY_MAX"):
        resolve_body_max()
    monkeypatch.setenv("RAFT_HTTP_READ_TIMEOUT_MS", "250")
    assert resolve_read_timeout_ms() == 250.0
    monkeypatch.setenv("RAFT_HTTP_READ_TIMEOUT_MS", "fast")
    with pytest.raises(ValueError, match="RAFT_HTTP_READ_TIMEOUT_MS"):
        resolve_read_timeout_ms()


def test_resolve_tenant_rate(monkeypatch):
    assert resolve_tenant_rate("10") == (10.0, 10.0)
    assert resolve_tenant_rate("2.5:40") == (2.5, 40.0)
    monkeypatch.setenv("RAFT_TENANT_RATE", "8:16")
    assert resolve_tenant_rate() == (8.0, 16.0)
    monkeypatch.delenv("RAFT_TENANT_RATE")
    assert resolve_tenant_rate() is None
    for bad in ("lots", "0", "-3", "5:0.2"):
        with pytest.raises(ValueError, match="RAFT_TENANT_RATE"):
            resolve_tenant_rate(bad)


def test_sanitize_tenant():
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant("team-a.prod_2") == "team-a.prod_2"
    assert sanitize_tenant('ev"il\r\nheader{}') == "ev_il__header__"
    assert len(sanitize_tenant("x" * 500)) == 64


def test_token_bucket_exact():
    """Quota exactness on synthetic time: burst admits exactly ``burst``,
    refill admits exactly ``rate`` per second, never above burst."""
    b = _TokenBucket(rate=2.0, burst=3.0, now=100.0)
    assert [b.consume(100.0) for _ in range(5)] == [
        True, True, True, False, False]
    assert b.consume(100.5) is True      # 0.5 s -> exactly one token
    assert b.consume(100.5) is False
    assert [b.consume(200.0) for _ in range(4)] == [
        True, True, True, False]         # refill capped at burst


def test_tenant_quotas_lru_bounded():
    q = TenantQuotas((1.0, 1.0), max_tenants=4)
    for i in range(100):
        q.admit(f"t{i}")
    assert q.status()["tenants_tracked"] <= 4
    assert TenantQuotas(None).admit("anyone") is True


def test_tenant_quota_churn_cannot_reset_spent_bucket():
    """Regression: churning fresh tenant names past max_tenants used to
    LRU-evict a spent bucket, so a blown tenant got a full burst back
    every ~max_tenants cheap requests. Eviction is now lossless-only
    (full buckets), spent buckets survive churn, newcomers share one
    overflow bucket."""
    q = TenantQuotas((0.001, 2.0), max_tenants=4)  # negligible refill
    assert q.admit("evil") and q.admit("evil")     # burst spent
    assert q.admit("evil") is False
    for t in ("a", "b", "c"):                      # fill the map
        q.admit(t)
    churn = [q.admit(f"churn{i}") for i in range(10)]
    # no tracked bucket is refilled-to-full -> every churn tenant shares
    # the ONE overflow bucket: exactly its burst admits, then denial
    assert churn == [True, True] + [False] * 8
    assert q.status()["overflow_bucket_active"]
    assert q.admit("evil") is False, "churn refilled a spent bucket"
    assert q.status()["tenants_tracked"] <= 4


def test_tenant_quota_lossless_eviction_of_idle_bucket():
    """A bucket that has refilled to full burst IS evictable — dropping
    it is lossless (re-creation starts full), so genuinely new tenants
    still get tracked slots as old ones go idle."""
    q = TenantQuotas((1.0, 2.0), max_tenants=2)
    q.admit("old")
    q.admit("recent")
    with q._lock:  # simulate 'old' idling long enough to refill fully
        q._buckets["old"].t_last -= 60.0
        q._buckets["recent"].tokens = 0.0
    assert q.admit("new") is True
    assert "old" not in q._buckets and "recent" in q._buckets
    assert q.status()["overflow_bucket_active"] is False


def test_tenant_label_set_bounded():
    """Metric labels: first max_tenants distinct names keep their own
    label, later names share __other__ — the registry keeps every label
    combination forever, so hostile name churn must not mint new ones
    (quota configured or not)."""
    q = TenantQuotas(None, max_tenants=2)
    assert q.label("a") == "a" and q.label("b") == "b"
    assert q.label("c") == TenantQuotas.OVERFLOW_LABEL
    assert q.label("a") == "a"  # established labels stay stable


# ---------------------------------------------------------------------------
# Live loopback battery
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def session(tiny_cfg):
    params = init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)
    return InferenceSession(
        params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2,
                      warmup_shapes=((H, W),), warmup_segmented=True))


@pytest.fixture(scope="module")
def service(session):
    svc = StereoService(session, ServiceConfig(max_queue=8)).start()
    yield svc
    svc.stop()


@pytest.fixture(scope="module")
def frontend(service):
    with HttpFrontend(service, HttpConfig(port=0)) as fe:
        yield fe


def post(fe, ct, body, headers=None, path="/v1/stereo"):
    """Well-formed-enough client: returns (status, headers, doc)."""
    req = urllib.request.Request(
        f"http://{fe.host}:{fe.port}{path}", data=body, method="POST",
        headers={"Content-Type": ct, **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), wire.decode_response(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


def raw_exchange(fe, data: bytes, timeout=10.0, half_close=False):
    """Fully hostile client: raw bytes out, (status, doc) parsed from
    whatever comes back before the server closes the connection."""
    with socket.create_connection((fe.host, fe.port),
                                  timeout=timeout) as s:
        s.sendall(data)
        if half_close:
            s.shutdown(socket.SHUT_WR)
        chunks = []
        try:
            while True:
                b = s.recv(65536)
                if not b:
                    break
                chunks.append(b)
        except (socket.timeout, TimeoutError):
            pass
    raw = b"".join(chunks)
    assert raw.startswith(b"HTTP/1."), raw[:80]
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    return status, (json.loads(body) if body.strip() else {})


def stereo_request_bytes(ct, body, extra_headers=()):
    head = (f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: {ct}\r\nContent-Length: {len(body)}\r\n")
    for k, v in extra_headers:
        head += f"{k}: {v}\r\n"
    return head.encode("latin-1") + b"\r\n" + body


def good_multipart(h=H, w=W, seed=0, rid=b"wire-0"):
    left, right = png_pair(h, w, seed)
    return wire.build_multipart({
        "left": wire.encode_image_png(left),
        "right": wire.encode_image_png(right), "id": rid}), (left, right)


def crash_count(fe) -> int:
    return sum(int(v) for _, v in
               fe.registry.series("raft_http_handler_crashes_total"))


def test_loopback_parity_mixed_shapes(service, frontend):
    """ISSUE acceptance: a mixed-shape request set over real sockets is
    byte-identical (disparity) and outcome-identical to the same set
    through ``StereoService.submit`` in-process."""
    # (44, 36) shares the warmed (40, 60) pad bucket; (72, 40) forces a
    # second bucket — "mixed-shape" covers both request AND program
    # diversity without a third compile.
    shapes = [(H, W), (44, 36), (72, 40), (H, W)]
    for i, (h, w) in enumerate(shapes):
        left, right = png_pair(h, w, seed=10 + i)
        (ct, body), _ = good_multipart(h, w, seed=10 + i,
                                       rid=f"par-{i}".encode())
        status, headers, over_wire = post(frontend, ct, body)
        assert status == 200, over_wire
        in_proc = service.submit({
            "id": f"par-{i}",
            "left": left.astype(np.float32)[None],
            "right": right.astype(np.float32)[None]}).result(timeout=600)
        assert in_proc["status"] == "ok"
        assert over_wire["status"] == "ok"
        assert over_wire["quality"] == in_proc["quality"]
        assert over_wire.get("retries", 0) == in_proc.get("retries", 0)
        assert over_wire["disparity"].tobytes() == \
            np.asarray(in_proc["disparity"], np.float32).tobytes()
        assert over_wire["id"] == in_proc["id"]


def test_hostile_battery_one_code_each(frontend):
    """Satellite 3: the malformed-request battery — one stable structured
    code per case, acceptor alive after ALL of them (proven by a clean
    200 at the end and a zero crash counter)."""
    crashes0 = crash_count(frontend)
    (ct, body), _ = good_multipart()
    boundary = ct.split("boundary=")[1]

    # (request bytes or callable, expected status, expected code)
    cases = []

    # empty body
    cases.append((stereo_request_bytes(ct, b""), 400, "empty_body"))
    # wrong content-type
    cases.append((stereo_request_bytes("text/plain", b"hi"), 415,
                  "unsupported_media_type"))
    # oversize declared content-length: rejected BEFORE any body bytes
    big = frontend.body_max + 1
    cases.append((
        f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\nContent-Type: {ct}\r\n"
        f"Content-Length: {big}\r\n\r\n".encode(), 413, "body_too_large"))
    # absurd but non-numeric content-length
    cases.append((
        f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\nContent-Type: {ct}\r\n"
        f"Content-Length: lots\r\n\r\n".encode(), 400,
        "bad_content_length"))
    # no content-length at all
    cases.append((
        f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
        f"Content-Type: {ct}\r\n\r\n".encode(), 411, "length_required"))
    # truncated body: declared full length, half sent, then half-close
    cases.append((stereo_request_bytes(ct, body)[:-len(body) // 2], 400,
                  "truncated_body"))
    # truncated multipart: consistent lengths, framing cut short
    cut = body[:-8]
    cases.append((stereo_request_bytes(ct, cut), 400, "bad_multipart"))
    # missing part
    _, only_left = wire.build_multipart({"left": b"x"}, boundary=boundary)
    cases.append((stereo_request_bytes(ct, only_left), 400,
                  "missing_part"))
    # garbage image bytes
    _, garb = wire.build_multipart(
        {"left": b"not a png", "right": b"also no"}, boundary=boundary)
    cases.append((stereo_request_bytes(ct, garb), 400, "bad_image"))
    # decompression bomb: 400 MP declared in ~300 file bytes
    _, bomb = wire.build_multipart(
        {"left": bomb_png(20_000, 20_000),
         "right": bomb_png(20_000, 20_000)}, boundary=boundary)
    cases.append((stereo_request_bytes(ct, bomb), 413, "image_too_large"))
    # unknown route / wrong method
    cases.append((stereo_request_bytes(ct, body).replace(
        b"/v1/stereo", b"/v1/nope", 1), 404, "unknown_route"))
    cases.append((stereo_request_bytes(ct, body).replace(
        b"POST", b"DELETE", 1), 405, "method_not_allowed"))
    # header flood: stdlib parser caps at 100 header lines -> JSON 431
    flood = (b"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
             + b"".join(b"X-Flood-%d: y\r\n" % i for i in range(150))
             + b"\r\n")
    cases.append((flood, 431, "too_many_headers"))
    # bad deadline via header on an otherwise good request
    cases.append((stereo_request_bytes(
        ct, body, extra_headers=[("X-Raft-Deadline-Ms", "soon")]), 400,
        "bad_deadline"))

    for i, (data, want_status, want_code) in enumerate(cases):
        status, doc = raw_exchange(frontend, data, half_close=True)
        assert status == want_status, (i, want_code, status, doc)
        assert doc.get("code") == want_code, (i, doc)
        assert doc.get("status") in ("rejected", "error"), (i, doc)

    # The acceptor survived every case: zero crashes, and a well-formed
    # request right after the storm still serves.
    assert crash_count(frontend) == crashes0
    status, _, doc = post(frontend, ct, body)
    assert status == 200 and doc["status"] == "ok"


def _responses_total(fe) -> int:
    return sum(int(v) for _, v in
               fe.registry.series("raft_http_responses_total"))


def test_client_disconnect_mid_response_survives(frontend):
    """A client that sends a full request then vanishes without reading
    the response still gets exactly ONE accounting entry ('ok' if the
    write landed in the dead socket's buffer, 'client_disconnect' if it
    didn't), and the listener keeps serving throughout."""
    before = _responses_total(frontend)
    (ct, body), _ = good_multipart(seed=3)
    with socket.create_connection((frontend.host, frontend.port),
                                  timeout=10) as s:
        s.sendall(stereo_request_bytes(ct, body))
        # close immediately: the response write hits a dead socket
    deadline = time.monotonic() + 120
    while _responses_total(frontend) == before:
        assert time.monotonic() < deadline, (
            "abandoned request never produced an accounting entry")
        # the in-flight request finishes asynchronously; poll healthz to
        # prove the listener keeps serving while it does
        status, _, _ = get(frontend, "/healthz")
        assert status == 200
        time.sleep(0.1)
    assert _responses_total(frontend) >= before + 1
    (ct, body), _ = good_multipart(seed=4)
    status, _, doc = post(frontend, ct, body)
    assert status == 200 and doc["status"] == "ok"


def test_stalled_body_evicted(service):
    """Slow-loris defense: a client that stalls mid-body is answered 408
    within the read deadline — the acceptor thread is never pinned."""
    with HttpFrontend(service, HttpConfig(
            port=0, read_timeout_ms=200.0)) as fe:
        (ct, body), _ = good_multipart(seed=5)
        head = stereo_request_bytes(ct, body)[:-len(body)]  # headers only
        t0 = time.monotonic()
        with socket.create_connection((fe.host, fe.port), timeout=30) as s:
            s.sendall(head + body[:100])  # 100 of len(body) bytes, then
            s.settimeout(30)              # silence — NOT a close
            chunks = []
            try:
                while True:
                    b = s.recv(65536)
                    if not b:
                        break
                    chunks.append(b)
            except (socket.timeout, TimeoutError):
                pass
        elapsed = time.monotonic() - t0
        raw = b"".join(chunks)
        assert b" 408 " in raw.split(b"\r\n", 1)[0], raw[:80]
        assert json.loads(raw.partition(b"\r\n\r\n")[2])["code"] == \
            "read_timeout"
        # 8 deadline factor x 0.2 s = 1.6 s worst case, plus slack
        assert elapsed < 10.0


def test_trickling_body_hits_whole_body_deadline(service):
    """The OTHER slow-loris: a client trickling bytes just under the
    per-read timeout never trips it — the whole-body deadline
    (BODY_DEADLINE_FACTOR read-timeouts) must evict it anyway. Guards
    the read1-per-recv regression: a buffered read(n) would absorb the
    trickle for one byte per recv and hold the thread ~forever."""
    with HttpFrontend(service, HttpConfig(
            port=0, read_timeout_ms=150.0)) as fe:
        (ct, body), _ = good_multipart(seed=11)
        head = stereo_request_bytes(ct, body)[:-len(body)]
        t0 = time.monotonic()
        raw = b""
        with socket.create_connection((fe.host, fe.port), timeout=30) as s:
            s.sendall(head)
            s.setblocking(False)
            sent = 0
            while time.monotonic() - t0 < 10.0:
                try:
                    raw += s.recv(65536)
                    if b"\r\n\r\n" in raw and raw.rstrip().endswith(b"}"):
                        break  # server answered: stop trickling
                except BlockingIOError:
                    pass
                if sent < len(body):
                    try:
                        s.send(body[sent:sent + 1])  # one byte per tick
                        sent += 1
                    except BlockingIOError:
                        pass
                time.sleep(0.05)  # well under the 150 ms per-read timeout
        elapsed = time.monotonic() - t0
        assert b" 408 " in raw.split(b"\r\n", 1)[0], raw[:120]
        assert json.loads(raw.partition(b"\r\n\r\n")[2])["code"] == \
            "read_timeout"
        # deadline = 8 x 0.15 s = 1.2 s; well before the trickle would
        # have delivered the full body
        assert 1.0 <= elapsed < 8.0, elapsed


def test_tenant_quota_exact_over_wire(service):
    """Per-tenant token buckets keyed by X-Raft-Tenant: burst admits
    exactly ``burst`` requests, the next is 429 + Retry-After, and an
    unrelated tenant is untouched."""
    with HttpFrontend(service, HttpConfig(
            port=0, tenant_rate="0.000001:2")) as fe:
        outcomes = []
        for i in range(4):
            (ct, body), _ = good_multipart(seed=6)
            status, headers, doc = post(
                fe, ct, body, headers={"X-Raft-Tenant": "hog"})
            outcomes.append((status, doc.get("code")))
        assert outcomes[:2] == [(200, None), (200, None)], outcomes
        assert outcomes[2:] == [(429, "quota_exceeded")] * 2, outcomes
        # the 429 told the client when to come back
        (ct, body), _ = good_multipart(seed=7)
        status, headers, doc = post(
            fe, ct, body, headers={"X-Raft-Tenant": "hog"})
        assert status == 429 and "Retry-After" in headers
        # quota is per tenant, not global
        status, _, doc = post(fe, ct, body,
                              headers={"X-Raft-Tenant": "polite"})
        assert status == 200 and doc["status"] == "ok"
        # exactness in the metrics: admitted == 2, quota_exceeded == 3
        by_outcome = {(labels["tenant"], labels["outcome"]): int(v)
                      for labels, v in fe.registry.series(
                          "raft_http_tenant_requests_total")}
        assert by_outcome[("hog", "admitted")] == 2
        assert by_outcome[("hog", "quota_exceeded")] == 3
        assert by_outcome[("polite", "admitted")] == 1


def test_drain_answers_503_service_draining(session):
    """SIGTERM semantics at the wire: a draining service answers late
    wire requests 503 ``service_draining`` + Retry-After through the SAME
    submit path in-process callers see, then quiesces clean."""
    svc = StereoService(session, ServiceConfig(max_queue=4)).start()
    with HttpFrontend(svc, HttpConfig(port=0)) as fe:
        svc.begin_drain()
        (ct, body), _ = good_multipart(seed=8)
        status, headers, doc = post(fe, ct, body)
        assert status == 503 and doc["code"] == "service_draining"
        assert headers.get("Retry-After")
        assert svc.drain() is True


def test_ingress_spans_join_the_service_timeline(service, frontend):
    """The trace opens at the WIRE: one timeline carries ingress_read and
    decode (frontend) ahead of admission/queue_wait (service) — not two
    disjoint traces stitched by a reader."""
    (ct, body), _ = good_multipart(seed=9, rid=b"span-probe")
    status, _, doc = post(frontend, ct, body)
    assert status == 200 and doc["status"] == "ok"
    probe = [t for t in service.tracer.timelines()
             if t.get("request_id") == "span-probe"]
    assert probe, "served request left no trace in the ring"
    kinds = [s["kind"] for s in probe[-1]["spans"]]
    for kind in ("ingress_read", "decode", "admission"):
        assert kind in kinds, (kind, kinds)
    assert kinds.index("ingress_read") < kinds.index("decode") \
        < kinds.index("admission")


def get(fe, path):
    try:
        with urllib.request.urlopen(
                f"http://{fe.host}:{fe.port}{path}", timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_healthz_and_metrics_are_real_endpoints(frontend):
    status, _, body = get(frontend, "/healthz")
    doc = json.loads(body)
    assert status == 200 and doc["ingress"]["endpoint"].endswith(
        str(frontend.port))
    assert doc["ingress"]["quota"]["limit"] is None
    # graftfleet (r20): generation identity + age are TOP-LEVEL fields —
    # the fleet router keys rolling deploys on fingerprint_id from the
    # one endpoint it already polls (not /debug/config) and reads
    # restarts off uptime_s.
    assert doc["fingerprint_id"] == \
        frontend.service.session.fingerprint_id()
    assert isinstance(doc["uptime_s"], float) and doc["uptime_s"] >= 0
    status, headers, body = get(frontend, "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    text = body.decode()
    assert "raft_http_responses_total" in text
    assert "raft_requests_total" in text  # the service's own registry
    # wrong-method probes get the stable codes
    status, _, doc = post(frontend, "text/plain", b"", path="/healthz")
    assert status == 405 and doc["code"] == "method_not_allowed"
    status, _, body = get(frontend, "/v1/stereo")
    assert status == 405 and json.loads(body)["code"] == \
        "method_not_allowed"


def test_disabled_tracer_id_backfill_is_harmless(frontend, monkeypatch):
    """A body-carried id with tracing disabled must not crash the
    handler: the disabled-tracing singleton is slotted, so the id
    backfill has to skip it (regression: AttributeError -> 500 on every
    id-carrying request)."""
    from raft_stereo_tpu.obs.tracing import NULL_TRACE
    monkeypatch.setattr(frontend.service, "tracer", type(
        "T", (), {"start_request": staticmethod(
            lambda rid=None: NULL_TRACE)})())
    before = crash_count(frontend)
    (ct, body), _ = good_multipart(rid=b"null-trace-id")
    status, _, doc = post(frontend, ct, body)
    assert status == 200 and doc["status"] == "ok", doc
    assert crash_count(frontend) == before


def test_stop_without_start_does_not_deadlock(service):
    """stop() on a never-started frontend must return (regression:
    BaseServer.shutdown() blocks on an event only serve_forever() sets,
    so an embedder's finally-cleanup hung forever)."""
    fe = HttpFrontend(service, HttpConfig(port=0))
    t = threading.Thread(target=fe.stop, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "stop() before start() deadlocked"


def test_expect_100_oversize_rejected_before_body(frontend):
    """A client sending ``Expect: 100-continue`` with an over-cap
    Content-Length gets the 413 verdict while still WAITING to send the
    body — no doomed upload is invited with a 100 Continue."""
    huge = frontend.body_max + 1
    head = (f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: multipart/form-data; boundary=x\r\n"
            f"Content-Length: {huge}\r\nExpect: 100-continue\r\n\r\n")
    status, doc = raw_exchange(frontend, head.encode("latin-1"))
    assert status == 413 and doc["code"] == "body_too_large", doc


def test_reject_drains_body_for_structured_answer(frontend):
    """Header-level rejects drain the (bounded) declared body before
    closing: closing with unread receive-buffer data emits TCP RST,
    which can destroy the structured response in flight. A client that
    sent its whole sizeable body to a doomed request must still read
    the JSON verdict."""
    body = b"z" * (128 << 10)
    head = (f"POST /nowhere HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: text/plain\r\n"
            f"Content-Length: {len(body)}\r\n\r\n")
    status, doc = raw_exchange(frontend, head.encode("latin-1") + body)
    assert status == 404 and doc["code"] == "unknown_route", doc


def test_method_message_names_method_and_head_is_bodyless(frontend):
    """405s name the actual method (regression: DELETE answered 'PUT is
    not supported'); HEAD responses are header-only per RFC 9110, and
    HEAD /healthz is the GET twin (LB/uptime probes commonly use HEAD —
    a 405 would rotate a healthy instance out)."""
    status, doc = raw_exchange(
        frontend, b"DELETE /v1/stereo HTTP/1.1\r\nHost: t\r\n\r\n")
    assert status == 405 and "DELETE" in doc["message"], doc
    status, doc = raw_exchange(
        frontend, b"HEAD /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
    assert status == 200 and doc == {}, "HEAD /healthz: headers only"
    status, doc = raw_exchange(
        frontend, b"HEAD /v1/stereo HTTP/1.1\r\nHost: t\r\n\r\n")
    assert status == 405 and doc == {}, "HEAD must carry no body"


def test_get_with_zero_content_length_keeps_keepalive(frontend):
    """``Content-Length: 0`` on a GET is a benign bodyless declaration
    (some clients send it on every request) — it must not be treated as
    a smuggled body and cost a reconnect per keep-alive probe."""
    probe = (b"GET /healthz HTTP/1.1\r\nHost: t\r\n"
             b"Content-Length: 0\r\n\r\n")

    def read_response(s):
        buf = b""
        while b"\r\n\r\n" not in buf:
            b_ = s.recv(65536)
            assert b_, "connection closed on a CL:0 keep-alive GET"
            buf += b_
        head, _, rest = buf.partition(b"\r\n\r\n")
        cl = next(int(ln.split(b":")[1]) for ln in head.split(b"\r\n")
                  if ln.lower().startswith(b"content-length"))
        while len(rest) < cl:
            b_ = s.recv(65536)
            assert b_, "connection closed mid-body"
            rest += b_
        return head

    with socket.create_connection((frontend.host, frontend.port),
                                  timeout=30) as s:
        for _ in range(2):  # second request proves the connection lived
            s.sendall(probe)
            head = read_response(s)
            assert head.startswith(b"HTTP/1.1 200"), head[:80]


def test_get_with_body_does_not_desync_keepalive(frontend):
    """A GET smuggling a body gets its bytes drained and the connection
    closed — leftover body bytes must never be parsed as the next
    request line (one request, one response, one accounting entry)."""
    before = _responses_total(frontend)
    body = b"x" * 10
    status, doc = raw_exchange(
        frontend,
        (f"GET /healthz HTTP/1.1\r\nHost: t\r\n"
         f"Content-Length: {len(body)}\r\n\r\n").encode("latin-1") + body)
    assert status == 200 and "queue" in doc
    assert _responses_total(frontend) == before + 1, \
        "body bytes were parsed as a second request"


def test_double_drain_is_noop(frontend):
    """A bodied request hitting both the route-level drain and the
    reject-level drain must not block: the first drain advances the
    consumed count, so the second is a no-op instead of a read-timeout
    stall on an empty socket (a cheap handler-pinning amplifier)."""
    body = b"x" * 100
    head = (f"GET /nowhere HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(body)}\r\n\r\n")
    t0 = time.monotonic()
    status, doc = raw_exchange(frontend, head.encode("latin-1") + body)
    assert status == 404 and doc["code"] == "unknown_route", doc
    assert time.monotonic() - t0 < 2.0, "second drain blocked"


def test_keepalive_resets_body_accounting(frontend):
    """A keep-alive connection reuses the handler instance: request B's
    reject drain must size itself from B's own body, not A's leftover
    consumed count (regression: a negative budget skipped the drain and
    closed with unread bytes — the RST the drain exists to prevent)."""
    (ct, body), _ = good_multipart(rid=b"ka-1")
    req1 = stereo_request_bytes(ct, body)
    tail = b"y" * 100
    req2 = (f"DELETE /v1/stereo HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(tail)}\r\n\r\n").encode("latin-1") + tail
    with socket.create_connection((frontend.host, frontend.port),
                                  timeout=60) as s:
        s.sendall(req1 + req2)
        s.shutdown(socket.SHUT_WR)
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
    raw = b"".join(chunks)
    assert raw.count(b"HTTP/1.1 ") == 2, raw[:200]
    first, second = raw.split(b"HTTP/1.1 ")[1:]
    assert first.startswith(b"200"), first[:80]
    assert second.startswith(b"405") and b"DELETE" in second, second[:200]


def test_unsupported_media_rejected_before_body_read(frontend):
    """The media type is in the HEADERS: an unsupported one answers 415
    without reading the declared body (previously it cost a full
    body_max-sized buffer before the same 415)."""
    huge = frontend.body_max  # declared, never sent
    head = (f"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: text/plain\r\n"
            f"Content-Length: {huge}\r\n\r\n")
    t0 = time.monotonic()
    status, doc = raw_exchange(frontend, head.encode("latin-1"),
                               half_close=True)  # EOF: drain is instant
    assert status == 415 and doc["code"] == "unsupported_media_type", doc
    assert time.monotonic() - t0 < frontend.body_deadline_s


def test_expect_100_header_stage_gates(service):
    """Expect: 100-continue runs EVERY header-stage gate before a 100
    invites the body: a quota-blown tenant gets its 429 while still
    waiting (non-consuming peek), wrong media types their 415."""
    cfg = HttpConfig(port=0, tenant_rate="0.001:1")  # burst 1, ~no refill
    with HttpFrontend(service, cfg) as fe:
        assert fe.quotas.admit("greedy")  # spend the burst
        head = (b"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: multipart/form-data; boundary=x\r\n"
                b"Content-Length: 100\r\nExpect: 100-continue\r\n"
                b"X-Raft-Tenant: greedy\r\n\r\n")
        status, doc = raw_exchange(fe, head)
        assert status == 429 and doc["code"] == "quota_exceeded", doc
        # the Expect-gated 429 is still a quota rejection served to that
        # tenant: the tenant series must not under-count Expect clients
        # (curl sends Expect by default for multipart bodies)
        counts = {(lb["tenant"], lb["outcome"]): int(v) for lb, v in
                  fe.registry.series("raft_http_tenant_requests_total")}
        assert counts.get(("greedy", "quota_exceeded")) == 1, counts
        head = (b"POST /v1/stereo HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: text/plain\r\n"
                b"Content-Length: 100\r\nExpect: 100-continue\r\n\r\n")
        status, doc = raw_exchange(fe, head)
        assert status == 415 and doc["code"] == "unsupported_media_type"


def test_connection_cap_immediate_503(service):
    """Aggregate connection bound: every per-connection defense bounds
    ONE connection, so the listener caps concurrent handler threads —
    a connection over the cap gets an immediate minimal 503
    ``overloaded`` (written on the acceptor, no thread spawned), and a
    freed slot serves again."""
    with HttpFrontend(service, HttpConfig(port=0, max_connections=1)) as fe:
        # Hold the single slot: connect and send nothing — the handler
        # thread parks in the request-line read under its own timeout.
        hold = socket.create_connection((fe.host, fe.port), timeout=10)
        try:
            time.sleep(0.1)  # let the acceptor hand off the connection
            status, doc = raw_exchange(
                fe, b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
            assert status == 503 and doc["code"] == "overloaded", doc
        finally:
            hold.close()
        # Slot released when the held connection's handler sees EOF.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            status, _ = raw_exchange(
                fe, b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
            if status == 200:
                break
            time.sleep(0.05)
        assert status == 200, "slot never freed after client close"


def test_decode_pool_shutdown_race_is_structured(service):
    """A handler that read its body but lost the race to stop()'s decode
    pool shutdown answers a structured 503 service_stopped, never a
    counted crash (regression: RuntimeError('cannot schedule new
    futures') -> 500 internal)."""
    with HttpFrontend(service, HttpConfig(port=0)) as fe:
        fe.decode_pool.shutdown(wait=False)
        before = crash_count(fe)
        (ct, body), _ = good_multipart(rid=b"pool-race")
        status, headers, doc = post(fe, ct, body)
        assert status == 503 and doc["code"] == "service_stopped", doc
        assert "Retry-After" in headers
        assert crash_count(fe) == before


# ---------------------------------------------------------------------------
# CLI decode offload (satellite 2)
# ---------------------------------------------------------------------------


def test_iter_decoded_pairs_order_and_bytes(tmp_path):
    """The batch driver's decode pool must be a pure pipelining change:
    same submission order, byte-identical decoded arrays vs the
    sequential path."""
    from serve_stereo import iter_decoded_pairs
    paths = []
    for i in range(7):
        left, right = png_pair(8, 12, seed=i)
        pl, pr = tmp_path / f"l{i}.png", tmp_path / f"r{i}.png"
        pl.write_bytes(wire.encode_image_png(left))
        pr.write_bytes(wire.encode_image_png(right))
        paths.append((str(pl), str(pr)))

    def decode_one(p):
        return read_image_rgb(p).astype(np.float32)[None]

    seq = [(f1, f2, (decode_one(f1), decode_one(f2))) for f1, f2 in paths]
    out = [(f1, f2, fut.result(timeout=30)) for f1, f2, fut in
           iter_decoded_pairs(paths, decode_one, workers=3)]
    assert [(a, b) for a, b, _ in out] == [(a, b) for a, b, _ in seq]
    for (_, _, (sl, sr)), (_, _, (ol, or_)) in zip(seq, out):
        assert sl.tobytes() == ol.tobytes()
        assert sr.tobytes() == or_.tobytes()


def test_iter_decoded_pairs_close_cancels_queued():
    """Closing the generator (the CLI's drain move) stops the pump and
    cancels every queued decode — the drain must not keep burning
    ~33 ms/sample on files whose requests will be stub-rejected."""
    from serve_stereo import iter_decoded_pairs
    calls = []

    def decode_one(p):
        calls.append(p)
        return p

    gen = iter_decoded_pairs([(f"l{i}", f"r{i}") for i in range(20)],
                             decode_one, workers=1)
    f1, _f2, fut = next(gen)
    fut.result(timeout=30)
    gen.close()
    time.sleep(0.2)  # any in-flight task would land within this
    # the one consumed pair decoded (2 calls); at most one more pair was
    # already mid-flight when close() cancelled the queue
    assert len(calls) <= 4, f"decode kept running after close: {calls}"


def test_cli_mode_validation_is_instant():
    """Missing -l/-r without --http_port dies before any model load or
    warmup compile (regression: the check ran after minutes of
    checkpoint read + jit)."""
    from serve_stereo import build_parser, serve
    args = build_parser().parse_args([])
    t0 = time.monotonic()
    with pytest.raises(SystemExit, match="batch mode needs"):
        serve(args)
    assert time.monotonic() - t0 < 1.0


def test_iter_decoded_pairs_bounded_lookahead():
    """The pool never decodes more than ``lookahead`` pairs ahead of the
    consumer — bounded memory regardless of glob size."""
    from serve_stereo import iter_decoded_pairs
    started = [0]
    lock = threading.Lock()

    def decode_one(_):
        with lock:
            started[0] += 1
        return np.zeros((1, 4, 4, 3), np.float32)

    pairs = [(f"l{i}", f"r{i}") for i in range(48)]
    gen = iter_decoded_pairs(pairs, decode_one, workers=2, lookahead=3)
    _, _, fut = next(gen)
    fut.result(timeout=30)
    time.sleep(0.3)  # ample time for an unbounded pool to run away
    # pump fills to 3 pairs, the one consumed yield refills once: at most
    # 4 pairs = 8 decodes may have STARTED while the consumer stalls —
    # not 96 (the unbounded failure this pins against).
    assert started[0] <= 8, started[0]
    n = 1
    for _, _, fut in gen:
        fut.result(timeout=30)
        n += 1
    assert n == 48 and started[0] == 96


def test_cli_ready_handshake_stdout_and_fd(tmp_path):
    """graftfleet satellite (r20): the live CLI's readiness handshake.

    ``--http_port 0`` must print exactly one machine-parseable
    ``RAFT_HTTP_PORT=<n>`` line to stdout AFTER the listening event
    (i.e. after warmup — a supervisor that reads it can route
    immediately), and ``--ready_fd`` must deliver the same line over an
    inherited pipe followed by EOF.  The advertised port must actually
    serve /healthz carrying the top-level fingerprint_id/uptime_s
    fields the fleet router consumes.  One real subprocess (~15 s tiny
    CPU model) — the price of pinning the contract on the production
    entry point rather than a refactored fragment of it.
    """
    import os
    import signal
    import subprocess
    import sys

    r_fd, w_fd = os.pipe()
    proc = subprocess.Popen(
        [sys.executable, "serve_stereo.py",
         "--http_port", "0", "--no_canary", "--ready_fd", str(w_fd),
         "--valid_iters", "2", "--segments", "2",
         "--n_gru_layers", "1", "--hidden_dims", "32", "32", "32",
         "--corr_levels", "2", "--corr_radius", "2",
         "--corr_implementation", "reg"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        pass_fds=(w_fd,), cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    os.close(w_fd)
    try:
        timer = threading.Timer(240.0, proc.kill)
        timer.start()
        seen_listening = False
        port = None
        try:
            for line in proc.stdout:
                line = line.strip()
                if line.startswith("{"):
                    doc = json.loads(line)
                    if doc.get("event") == "listening":
                        seen_listening = True
                    continue
                if line.startswith("RAFT_HTTP_PORT="):
                    assert seen_listening, (
                        "handshake printed before the listening event")
                    port = int(line.split("=", 1)[1])
                    break
        finally:
            timer.cancel()
        assert port is not None, "no RAFT_HTTP_PORT handshake on stdout"
        # --ready_fd: same line over the inherited pipe, then EOF.
        with os.fdopen(r_fd, "r") as ready_pipe:
            r_fd = None
            assert ready_pipe.read() == f"RAFT_HTTP_PORT={port}\n"
        # The advertised port serves, and /healthz carries the fleet
        # router's generation-identity fields at the top level.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            assert resp.status == 200
            health = json.loads(resp.read())
        assert isinstance(health["fingerprint_id"], str)
        assert len(health["fingerprint_id"]) == 12
        assert health["uptime_s"] >= 0
        proc.send_signal(signal.SIGTERM)
        proc.communicate(timeout=120)
        assert proc.returncode == 0
    finally:
        if r_fd is not None:
            os.close(r_fd)
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

"""graftguard battery (DESIGN.md r13): hang watchdogs, generation
bounces, bounded per-request retries, uploader crash-proofing, drain
semantics, and the exactly-once resolution contract under stop/tick
races.

Everything runs on CPU with the tiny model config.  All *deadline math*
runs on FakeClock (an injected 50 s hang costs zero wall time); the only
real-time waiting is bounded thread rendezvous (waiting for an injected
crash to actually kill its thread), same as the rest of the serving
battery.  No Supervisor monitor thread runs anywhere here: every test
drives ``Supervisor.check_now()`` synchronously, so detection ordering
is deterministic.
"""

import time

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import ChaosPlan, FakeClock
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.obs.flight import FlightRecorder
from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                   SessionConfig, StereoService, Supervisor)
from raft_stereo_tpu.serve.supervise import (DEFAULT_DRAIN_GRACE_MS,
                                             DEFAULT_RETRY_BUDGET,
                                             InFlight, InvocationWatch,
                                             WATCHDOG_FACTOR,
                                             WATCHDOG_WARM_FACTOR,
                                             resolve_drain_grace_ms,
                                             resolve_retry_budget,
                                             resolve_watchdog_ms)

pytestmark = pytest.mark.serve

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # not multiples of 32: every request really is padded


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pairs():
    rng = np.random.default_rng(7)
    return [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32)[None],
             rng.uniform(0, 255, (H, W, 3)).astype(np.float32)[None])
            for _ in range(4)]


def make_service(params, cfg, *, plan=None, flight=None, retry_budget=2,
                 watchdog_ms=5000.0, max_queue=16):
    """Batched service with supervision config but NO monitor thread:
    tests drive ``check_now`` by hand for deterministic ordering."""
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      canary=False),
        fault_plan=plan, clock=FakeClock(), flight=flight)
    svc = StereoService(session, ServiceConfig(
        max_queue=max_queue, watchdog_ms=watchdog_ms,
        retry_budget=retry_budget, supervise=False)).start()
    return session, svc


def wait_real(predicate, timeout=30.0, what="condition"):
    """Bounded real-time rendezvous with an injected thread death (the
    deadline MATH stays on FakeClock; this only waits for the OS to run
    the victim thread)."""
    deadline = time.monotonic() + timeout
    while not predicate():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        time.sleep(0.002)


def submit(svc, pairs, rid, **kw):
    left, right = pairs[rid % len(pairs)]
    return svc.submit({"id": rid, "left": left, "right": right, **kw})


# ---------------------------------------------------------------------------
# Watchdog deadline math (pure, FakeClock-free).


def test_watchdog_deadline_math():
    """Steady = max(EMA x factor, floor); EMA-less steady = floor alone;
    warming (compile-inclusive) = floor x warm grace, never the EMA rule."""
    def inv(warming, est):
        return InFlight(token=0, program="p", kind="advance",
                        warming=warming, est=est, t0=0.0)
    floor = 2.0
    assert InvocationWatch.allowed_s(inv(False, None), floor) == floor
    assert InvocationWatch.allowed_s(inv(False, 10.0), floor) == \
        10.0 * WATCHDOG_FACTOR
    assert InvocationWatch.allowed_s(inv(False, 0.1), floor) == floor
    assert InvocationWatch.allowed_s(inv(True, 0.1), floor) == \
        floor * WATCHDOG_WARM_FACTOR


def test_invocation_watch_overdue_on_fake_clock():
    clk = FakeClock()
    watch = InvocationWatch(clk)
    token = watch.begin("prog", "advance", warming=False, est=None)
    assert watch.count == 1
    assert watch.overdue(clk.now(), 5.0) == []
    clk.sleep(50.0)
    rows = watch.overdue(clk.now(), 5.0)
    assert len(rows) == 1
    inv, age, allowed = rows[0]
    assert inv.kind == "advance" and age == 50.0 and allowed == 5.0
    watch.end(token)
    assert watch.count == 0 and watch.overdue(clk.now(), 5.0) == []


def test_supervision_knobs_resolve_env(monkeypatch):
    """Explicit config > env knob > default — the SERVE_ENV_KNOBS
    contract for all three supervision knobs."""
    for name in ("RAFT_WATCHDOG_MS", "RAFT_RETRY_BUDGET",
                 "RAFT_DRAIN_GRACE_MS"):
        monkeypatch.delenv(name, raising=False)
    assert resolve_watchdog_ms() == 0.0          # library default: off
    assert resolve_retry_budget() == DEFAULT_RETRY_BUDGET
    assert resolve_drain_grace_ms() == DEFAULT_DRAIN_GRACE_MS
    monkeypatch.setenv("RAFT_WATCHDOG_MS", "1234")
    monkeypatch.setenv("RAFT_RETRY_BUDGET", "7")
    monkeypatch.setenv("RAFT_DRAIN_GRACE_MS", "2500")
    assert resolve_watchdog_ms() == 1234.0
    assert resolve_retry_budget() == 7
    assert resolve_drain_grace_ms() == 2500.0
    assert resolve_watchdog_ms(10.0) == 10.0     # explicit beats env
    assert resolve_retry_budget(0) == 0
    assert resolve_drain_grace_ms(1.0) == 1.0


# ---------------------------------------------------------------------------
# Satellite bugfix pin: a mid-run uploader crash must never strand its
# joiners' Futures — structured ``upload_failed``, retries recorded, and
# the watchdog bounce restores service on a fresh uploader.


def test_uploader_crash_is_structured_upload_failed(tiny_params, tiny_cfg,
                                                    pairs):
    session, svc = make_service(tiny_params, tiny_cfg,
                                plan=ChaosPlan(crash_uploads=(0,)),
                                retry_budget=0)
    try:
        r = submit(svc, pairs, 0).result(timeout=60)
        assert r["status"] == "error" and r["code"] == "upload_failed"
        hb = svc.supervision_status()["heartbeats"]
        assert hb["uploader_dead"] is not None
        # The watchdog heals it: uploader_dead trip -> generation bounce
        # -> fresh uploader -> the next request serves clean.
        sup = Supervisor(svc, watchdog_s=5.0)
        trips = sup.check_now()
        assert [t.kind for t in trips] == ["uploader_dead"]
        r2 = submit(svc, pairs, 1).result(timeout=60)
        assert r2["status"] == "ok" and r2["quality"] == "full"
        st = svc.supervision_status()
        assert st["generation"] == 2
        assert st["restarts"] == {"uploader_dead": 1}
    finally:
        svc.stop()


def test_uploader_crash_burns_bounded_retries(tiny_params, tiny_cfg, pairs):
    """Without a bounce, every re-admission meets the same dead uploader:
    the budget bounds the loop and the final response records it
    (``retries: k`` — the response contract)."""
    session, svc = make_service(tiny_params, tiny_cfg,
                                plan=ChaosPlan(crash_uploads=(0,)),
                                retry_budget=3)
    try:
        r = submit(svc, pairs, 0).result(timeout=60)
        assert r["status"] == "error" and r["code"] == "upload_failed"
        assert r["retries"] == 3
        assert int(session.registry.value(
            "raft_request_retries_total")) == 3
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Acceptance pin: injected device hang provably recovers — watchdog
# fires, the generation bounces, the request retries inside its budget
# (success) or fails ``device_hang`` (budget exhausted).  FakeClock: the
# 50 s hang costs zero wall time in the deadline math.


def hang_service(tiny_params, tiny_cfg, *, retry_budget):
    # Invoke ordinals with one warm request ahead: warm rides
    # prepare(0) advance(1) advance(2) epilogue(3); the victim's steady
    # advance is ordinal 5 — a STEADY hang, governed by the floor, not
    # the warm grace.
    plan = ChaosPlan(hang_invokes={5: 50.0}, hang_cap_s=20.0)
    return make_service(tiny_params, tiny_cfg, plan=plan,
                        retry_budget=retry_budget)


def test_device_hang_recovers_within_budget(tiny_params, tiny_cfg, pairs):
    session, svc = hang_service(tiny_params, tiny_cfg, retry_budget=2)
    try:
        warm = submit(svc, pairs, 0).result(timeout=120)
        assert warm["status"] == "ok"
        fut = submit(svc, pairs, 1)
        assert session.faults.wait_hang_entered(1, timeout=30)
        sup = Supervisor(svc, watchdog_s=5.0)
        trips = sup.check_now()
        assert [t.kind for t in trips] == ["device_hang"]
        r = fut.result(timeout=60)
        assert r["status"] == "ok" and r["quality"] == "full"
        assert r["retries"] == 1   # the bounce re-admission, recorded
        st = svc.supervision_status()
        assert st["generation"] == 2
        assert st["restarts"] == {"device_hang": 1}
        assert st["watchdog_trips"] == {"device_hang": 1}
        # /healthz carries the supervision block end to end.
        assert svc.status()["supervision"]["generation"] == 2
    finally:
        svc.stop()


def test_device_hang_budget_exhausted_fails_device_hang(tiny_params,
                                                        tiny_cfg, pairs):
    session, svc = hang_service(tiny_params, tiny_cfg, retry_budget=0)
    try:
        assert submit(svc, pairs, 0).result(timeout=120)["status"] == "ok"
        fut = submit(svc, pairs, 1)
        assert session.faults.wait_hang_entered(1, timeout=30)
        Supervisor(svc, watchdog_s=5.0).check_now()
        r = fut.result(timeout=60)
        assert r["status"] == "error" and r["code"] == "device_hang"
        assert "retries" not in r   # budget 0: no re-admission happened
    finally:
        svc.stop()


def test_real_hang_trips_once_not_every_sweep(tiny_params, tiny_cfg):
    """A REAL device hang never calls watch.end(): without trip memory
    every sweep would re-detect it and bounce each fresh, healthy
    generation in a poll-period storm. One hang = one bounce."""
    session, svc = make_service(tiny_params, tiny_cfg)
    try:
        token = session.watch.begin("prog", "advance", warming=False,
                                    est=None)
        session.clock.sleep(60.0)
        sup = Supervisor(svc, watchdog_s=5.0)
        assert [t.kind for t in sup.check_now()] == ["device_hang"]
        assert sup.check_now() == []          # same wedged invocation
        assert sup.check_now() == []
        st = svc.supervision_status()
        assert st["generation"] == 2          # exactly ONE bounce
        assert st["restarts"] == {"device_hang": 1}
        # The invocation ending clears the memory: a NEW hang trips.
        session.watch.end(token)
        session.watch.begin("prog", "advance", warming=False, est=None)
        session.clock.sleep(60.0)
        assert [t.kind for t in sup.check_now()] == ["device_hang"]
        assert svc.supervision_status()["generation"] == 3
    finally:
        svc.stop()


def test_wedged_uploader_trips_stalled(tiny_params, tiny_cfg):
    """An uploader wedged mid-transfer (alive, not dead) is otherwise
    invisible — the tick loop keeps beating while nothing uploads; the
    busy_since age detector bounces onto a fresh uploader."""
    session, svc = make_service(tiny_params, tiny_cfg)
    try:
        svc._scheduler.uploader.busy_since = session.clock.now()
        session.clock.sleep(60.0)   # > floor(5) x stall_factor(4)
        sup = Supervisor(svc, watchdog_s=5.0)
        assert [t.kind for t in sup.check_now()] == ["uploader_stalled"]
        st = svc.supervision_status()
        assert st["generation"] == 2
        assert st["restarts"] == {"uploader_stalled": 1}
        assert sup.check_now() == []   # fresh uploader: not busy
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Acceptance pin: injected tick-loop crash provably recovers — the loop
# wrapper records the death on the heartbeat, the watchdog bounces the
# generation, the stranded mid-batch row re-admits and serves.


def test_tick_crash_recovers(tiny_params, tiny_cfg, pairs):
    # Work ticks are deterministic (idle polls don't count): request 0
    # consumes ticks 0-1; the crash after tick 2 kills the loop with
    # request 1 mid-batch (joined + one segment advanced).
    session, svc = make_service(tiny_params, tiny_cfg,
                                plan=ChaosPlan(crash_ticks=(2,)),
                                retry_budget=2)
    try:
        assert submit(svc, pairs, 0).result(timeout=120)["status"] == "ok"
        fut = submit(svc, pairs, 1)
        wait_real(lambda: svc._heartbeat.died is not None,
                  what="injected tick crash to kill the loop thread")
        sup = Supervisor(svc, watchdog_s=5.0)
        trips = sup.check_now()
        assert [t.kind for t in trips] == ["tick_crashed"]
        r = fut.result(timeout=60)
        assert r["status"] == "ok" and r["quality"] == "full"
        assert r["retries"] == 1
        st = svc.supervision_status()
        assert st["generation"] == 2
        assert st["restarts"] == {"tick_crashed": 1}
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Flight-recorder sequence numbering survives a generation bounce: the
# recorder is session-owned (one per lineage, not per generation), so
# bounce records and post-bounce breach records share one monotone
# sequence — eviction order stays oldest-first through a restart storm.


def test_flight_seq_survives_generation_bounce(tiny_params, tiny_cfg,
                                               pairs, tmp_path):
    flight = FlightRecorder(str(tmp_path), limit=16)
    session, svc = make_service(tiny_params, tiny_cfg, flight=flight)
    try:
        assert svc.bounce()
        assert submit(svc, pairs, 0).result(timeout=120)["status"] == "ok"
        assert svc.bounce()
        session.flight.record({"post": True}, trace_id="after")
        paths = flight.records()
        seqs = [int(p.split("flight-")[1][:6]) for p in paths]
        assert seqs == [0, 1, 2]       # monotone across both bounces
        assert "bounce-g2" in paths[0] and "bounce-g3" in paths[1]
        import json
        doc = json.loads(open(paths[0]).read())
        assert doc["reasons"] == ["watchdog:manual"]
        assert doc["generation"] == {"from": 1, "to": 2}
        st = svc.supervision_status()
        assert st["generation"] == 3 and st["restarts"] == {"manual": 2}
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Exactly-once resolution: stop() racing an in-flight batched tick must
# resolve every admitted row exactly once — no abandoned Future, and the
# outcome counters reconcile (a double resolve would double-count; the
# request-claim guard in the service pins this).


def test_stop_racing_tick_resolves_exactly_once(tiny_params, tiny_cfg,
                                                pairs):
    session = InferenceSession(
        tiny_params, tiny_cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=4,
                      canary=False),
        clock=FakeClock())
    reg = session.registry

    def outcome_total():
        return sum(int(v) for labels, v in
                   reg.series("raft_requests_total")
                   if labels["outcome"] != "degraded")

    svc = StereoService(session, ServiceConfig(max_queue=16,
                                               supervise=False))
    for round_no in range(3):   # three interleavings of stop vs tick
        before = outcome_total()
        svc.start()
        futs = [submit(svc, pairs, i) for i in range(6)]
        if round_no == 1:
            # Let the scheduler provably reach mid-flight before racing.
            deadline = time.monotonic() + 30
            while svc._scheduler.active_rows == 0 and \
                    time.monotonic() < deadline:
                time.sleep(0.001)
        svc.stop()
        responses = [f.result(timeout=60) for f in futs]
        for r in responses:
            assert r["status"] in ("ok", "rejected"), r
            if r["status"] == "rejected":
                assert r["code"] in ("service_stopped", "not_running")
        assert outcome_total() - before == len(futs), (
            "outcome counters disagree with resolved Futures — a row was "
            "double-resolved or dropped")


def test_queue_depth_gauge_registered(tiny_params, tiny_cfg, pairs):
    session, svc = make_service(tiny_params, tiny_cfg)
    try:
        assert submit(svc, pairs, 0).result(timeout=120)["status"] == "ok"
        assert "raft_queue_depth" in svc.metrics_text()
        assert int(session.registry.value("raft_queue_depth")) == 0
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Drain contract (library level; the CLI signal path rides these).


def test_drain_rejects_new_and_finishes_admitted(tiny_params, tiny_cfg,
                                                 pairs):
    session, svc = make_service(tiny_params, tiny_cfg)
    try:
        fut = submit(svc, pairs, 0)
        svc.begin_drain()
        late = submit(svc, pairs, 1).result(timeout=10)
        assert late["status"] == "rejected"
        assert late["code"] == "service_draining"
        # Admitted work runs to its exit with an honest label.
        r = fut.result(timeout=120)
        assert r["status"] == "ok" and r["quality"] == "full"
        assert svc.supervision_status()["draining"]
        assert svc.drain(grace_s=30.0)   # quiesces clean -> True
    finally:
        svc.stop()


def test_drain_is_idempotent_and_counts(tiny_params, tiny_cfg, pairs):
    session, svc = make_service(tiny_params, tiny_cfg)
    svc.begin_drain()
    svc.begin_drain()
    r = submit(svc, pairs, 0).result(timeout=10)
    assert r["code"] == "service_draining"
    counts = {labels["outcome"]: int(v) for labels, v in
              session.registry.series("raft_requests_total")}
    assert counts.get("rejected:service_draining") == 1
    svc.stop()

"""graftlane battery (r24): int8 packed containers for the per-iteration
feature/context lanes (RAFT_LANE_PACK8).

Pins, mirroring the r19 corr-pack8 discipline (tests/test_corr.py):

- container error budget: dequant may differ from the source rows by at
  most ``scale/2`` (symmetric scheme, scale = per-sample amax/127), and
  zero pad rows survive packing as EXACT zeros;
- per-SAMPLE scales: batched rows quantize independently of their
  batchmates (the r19 review-round regression class);
- the lane ledger's exact arithmetic (plan_lane_dma_bytes) and the
  <= 0.6x acceptance ratio across geometries, odd widths included;
- the lane8 kernels' in-register dequant matches the host dequant at f32
  to within FMA fusion of the ``q * scale`` multiply (a few ULPs, never
  a quantization-sized error), for both the serial GRU kernel and the
  resident mega-kernel;
- STE gradients: the container is zero-cotangent and the XLA-oracle
  backward reads ``context`` — so grads are BITWISE identical packed vs
  unpacked;
- the encoder-exit q8 epilogue (stream_head_conv_q8 / stream_resblock_q8)
  is bitwise identical to host-packing the streamed bf16 output;
- end-to-end: the armed forward == prepare + segments bitwise (containers
  ride the carry), prepare_warm consumes packed containers bit-identically
  to the cold prepare, and RAFT_LANE_PACK8 unset vs "0" is byte-for-byte
  the same program output with a container-free carry.

The end-to-end ERROR budget is op-level by design: like corr_pack8, the
deployment-weights protection is the serving parity canary (the lane_pack8
rung trips when drift leaves the band), not a random-weights bound — at
random init the GRU amplifies quantization noise chaotically (measured
~3.5 px at the canary geometry for the LANDED corr_pack8 rung too).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.corr.pallas_reg import (feature_scale8,
                                             quantize_pack_feature8,
                                             unpack_feature8)
from raft_stereo_tpu.models import (init_raft_stereo, raft_stereo_forward,
                                    raft_stereo_prepare, raft_stereo_segment)
from raft_stereo_tpu.models.update import init_conv_gru
from raft_stereo_tpu.ops.pallas_stream import (fused_conv_gru,
                                               fused_conv_gru_fwd_impl,
                                               plan_lane_dma_bytes,
                                               prepare_gru_context,
                                               prepare_gru_context_any)

pytestmark = pytest.mark.kernel_battery


# ---------------------------------------------------------------------------
# Container: error budget, zero rows, batched independence, lane math.


@pytest.mark.parametrize("w", [40, 37, 78, 186])
def test_lane_container_error_budget_pinned(rng, w):
    """Dequant error <= scale/2 per sample at quad and non-quad widths,
    and rows that are exactly zero stay EXACTLY zero (symmetric grid:
    q == 0 <-> 0.0 — the padding contract prepare_gru_context relies on),
    with the (B, H, ceil(W/4), C) fp32 container layout pinned."""
    x = jnp.asarray(rng.standard_normal((2, 12, w, 16)), jnp.float32)
    x = x.at[:, -3:].set(0.0)  # zero pad rows
    scale = feature_scale8(x)
    pk = quantize_pack_feature8(x, scale)
    assert pk.shape == (2, 12, -(-w // 4), 16) and pk.dtype == jnp.float32
    assert scale.shape == (2, 1, 1, 1)
    got = unpack_feature8(pk, scale, w)
    err = np.asarray(jnp.max(jnp.abs(got - x), axis=(1, 2, 3)))
    bound = 0.5 * np.asarray(scale).reshape(-1)
    assert (err <= bound * (1 + 1e-4)).all(), (err, bound)
    assert float(jnp.max(jnp.abs(got[:, -3:]))) == 0.0


def test_lane_container_batched_rows_independent(rng):
    """Per-sample scales: one high-contrast batchmate must not move
    another sample's quantization grid — sample i's container bytes and
    scale are BITWISE equal to the solo B=1 pack of the same rows."""
    x = jnp.asarray(rng.standard_normal((2, 8, 40, 16)), jnp.float32)
    x = x.at[1].multiply(23.0)  # outlier batchmate
    scale = feature_scale8(x)
    pk = quantize_pack_feature8(x, scale)
    for i in range(2):
        solo_scale = feature_scale8(x[i:i + 1])
        solo_pk = quantize_pack_feature8(x[i:i + 1], solo_scale)
        assert np.asarray(scale[i:i + 1]).tobytes() == \
            np.asarray(solo_scale).tobytes(), f"row {i}"
        assert np.asarray(pk[i:i + 1]).tobytes() == \
            np.asarray(solo_pk).tobytes(), f"row {i}"


def test_plan_lane_dma_ratio_battery():
    """The lane ledger's exact arithmetic: bf16 rows stream h*w*3*ch*2
    bytes per level, containers h*ceil(w/4)*3*ch*4 bytes plus one (1,1)
    f32 scale — and the acceptance ratio <= 0.6 holds at headline, the
    serve bucket, odd widths and shallow pyramids alike."""
    # Exact spot check at headline (1/4-res 504x744, three levels).
    bf16 = plan_lane_dma_bytes(2016, 2976, pack8=False)
    int8 = plan_lane_dma_bytes(2016, 2976, pack8=True)
    assert bf16 == sum((-(-504 // 2 ** i)) * (-(-744 // 2 ** i)) * 3 * 128 * 2
                       for i in range(3))
    assert int8 == sum((-(-504 // 2 ** i)) * (-(-(-(-744 // 2 ** i)) // 4))
                       * 3 * 128 * 4 + 4 for i in range(3))
    for h_img, w_img, kw in [(2016, 2976, {}), (384, 1248, {}),
                             (200, 316, {}), (40, 60, {"n_levels": 2}),
                             (377, 1111, {}), (64, 96, {"ch": 32})]:
        r = (plan_lane_dma_bytes(h_img, w_img, pack8=True, **kw)
             / plan_lane_dma_bytes(h_img, w_img, pack8=False, **kw))
        assert r <= 0.6, (h_img, w_img, kw, r)


# ---------------------------------------------------------------------------
# Kernels: in-register dequant parity + STE gradients.


def _gru_case(key, h_, w_, ch, dtype):
    p = init_conv_gru(key, ch, 2 * ch)
    ks = jax.random.split(key, 6)
    h = jax.random.normal(ks[0], (1, h_, w_, ch), dtype) * 0.5
    xs = [jax.random.normal(k, (1, h_, w_, ch), dtype) for k in ks[1:3]]
    ctx = tuple(jax.random.normal(k, (1, h_, w_, ch), dtype) * 0.3
                for k in ks[3:6])
    return p, h, xs, ctx


def test_lane_gru_kernel_matches_host_dequant_to_fma_ulps(monkeypatch):
    """The lane8 GRU kernel's in-register dequant (_lane8_rows: four
    sign-extending byte extracts, one f32 multiply by the per-sample
    scale) matches feeding the host-dequantized rows to the bf16-path
    kernel to within FMA fusion: the ONLY divergence is that XLA may fuse
    ``q * scale`` into the accumulating add (product never rounded to
    f32), so the budget is a few ULPs of the tanh-bounded hidden state —
    NOT a quantization-sized error (that would be ~scale/2 ≈ 5e-3)."""
    dtype = jnp.float32
    w_ = 24
    p, h, xs, ctx = _gru_case(jax.random.PRNGKey(0), 16, w_, 32, dtype)
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    packed = prepare_gru_context_any(p, ctx, dtype)
    assert isinstance(packed, tuple)
    pk, scale = packed
    rows = unpack_feature8(pk, scale, w_).astype(dtype)
    got, _ = fused_conv_gru_fwd_impl(p, h, packed, *xs)
    ref, _ = fused_conv_gru_fwd_impl(p, h, rows, *xs)
    err = float(jnp.max(jnp.abs(got - ref)))
    assert err <= 1e-6, err  # measured 2.4e-7 (1-2 ULPs)


def _resident_case(key, B, hh, ww, ch, d, dtype, levels=4, radius=4):
    from raft_stereo_tpu.corr.pallas_reg import build_corr_operands
    from raft_stereo_tpu.models.update import (init_flow_head,
                                               init_motion_encoder)
    cfg = RAFTStereoConfig(corr_levels=levels, corr_radius=radius)
    ks = jax.random.split(key, 12)
    f1 = jax.random.normal(ks[0], (B, hh, ww, d), dtype)
    f2 = jax.random.normal(ks[1], (B, hh, ww, d), dtype)
    ops = build_corr_operands(f1, f2, num_levels=levels, radius=radius,
                              out_dtype=dtype)
    coords_x = jax.random.uniform(ks[2], (B, hh, ww), jnp.float32) * ww
    flow = jnp.concatenate(
        [jax.random.normal(ks[3], (B, hh, ww, 1), dtype),
         jnp.zeros((B, hh, ww, 1), dtype)], -1)
    penc = init_motion_encoder(ks[4], cfg)
    pgru = init_conv_gru(ks[5], ch, 128 + ch)
    phead = init_flow_head(ks[6], ch, 64, 2)
    h = jax.random.normal(ks[7], (B, hh, ww, ch), dtype) * 0.5
    up = jax.random.normal(ks[8], (B, hh, ww, ch), dtype)
    ctx = tuple(jax.random.normal(k, (B, hh, ww, ch), dtype) * 0.3
                for k in ks[9:12])
    return ops, coords_x, flow, penc, pgru, phead, h, up, ctx


def test_lane_resident_kernel_matches_host_dequant_to_fma_ulps(monkeypatch):
    """Same FMA-ULP pin for the resident mega-kernel (its czrq dequant
    shares _lane8_rows with the serial kernels) — and the loud rejection
    of a packed czrq arriving with the switch disarmed (stale
    quantization must never serve silently)."""
    from raft_stereo_tpu.ops.pallas_resident import fused_iter_fwd_impl
    dtype = jnp.float32
    ww = 24
    (ops, coords_x, flow, penc, pgru, phead, h, up,
     ctx) = _resident_case(jax.random.PRNGKey(3), 1, 16, ww, 32, 16, dtype)
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    packed = prepare_gru_context_any(pgru, ctx, dtype)
    assert isinstance(packed, tuple)
    pk, scale = packed
    rows = unpack_feature8(pk, scale, ww).astype(dtype)
    h_got, dx_got = fused_iter_fwd_impl(penc, pgru, phead, ops, h, packed,
                                        coords_x, flow, up)
    h_ref, dx_ref = fused_iter_fwd_impl(penc, pgru, phead, ops, h, rows,
                                        coords_x, flow, up)
    assert float(jnp.max(jnp.abs(h_got - h_ref))) <= 1e-6   # measured 1.8e-7
    assert float(jnp.max(jnp.abs(dx_got - dx_ref))) <= 1e-5  # measured 1.4e-6
    # Kill-switch lifetime contract: a packed state outliving the armed
    # window fails LOUDLY instead of dequantizing stale bytes.
    monkeypatch.delenv("RAFT_LANE_PACK8")
    with pytest.raises(RuntimeError, match="RAFT_LANE_PACK8"):
        fused_iter_fwd_impl(penc, pgru, phead, ops, h, packed,
                            coords_x, flow, up)


def test_lane_ste_grads_bitwise(monkeypatch):
    """The czrq operand — rows or (container, scale) pair — carries ZERO
    cotangent; the oracle backward reads ``context``. So grads wrt
    (params, h, context, x) are BITWISE identical packed vs unpacked."""
    dtype = jnp.float32
    p, h, xs, ctx = _gru_case(jax.random.PRNGKey(1), 16, 24, 32, dtype)
    rows = prepare_gru_context(p, ctx, dtype)
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    packed = prepare_gru_context_any(p, ctx, dtype)
    assert isinstance(packed, tuple)

    def loss(p_, czrq, h_, ctx_, xs_):
        return jnp.sum(fused_conv_gru(p_, h_, czrq, ctx_, *xs_)
                       .astype(jnp.float32))

    g_rows = jax.grad(loss, argnums=(0, 2, 3, 4))(p, rows, h, ctx, xs)
    g_pack = jax.grad(loss, argnums=(0, 2, 3, 4))(p, packed, h, ctx, xs)
    for a, b in zip(jax.tree_util.tree_leaves(g_rows),
                    jax.tree_util.tree_leaves(g_pack)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    # And the container itself is zero-cotangent.
    g_czrq = jax.grad(loss, argnums=1)(p, packed, h, ctx, xs)
    assert all(float(jnp.max(jnp.abs(leaf))) == 0.0
               for leaf in jax.tree_util.tree_leaves(g_czrq))


# ---------------------------------------------------------------------------
# Encoder exit: the q8 epilogue's bitwise-to-host-pack contract.


def test_encoder_q8_epilogue_bitwise_vs_host_pack(monkeypatch):
    """stream_head_conv_q8 / stream_resblock_q8 write the container +
    scale DIRECTLY from the streaming pass — bitwise identical to
    host-packing the streamed bf16 output (the epilogue quantizes the
    bf16-rounded rows with the same amax scale arithmetic as
    quantize_pack_feature8), with zero cotangent, and the q8 gates refuse
    whenever the lane is disarmed."""
    from raft_stereo_tpu.models.layers import init_conv, init_residual_block
    from raft_stereo_tpu.ops.pallas_encoder import (
        head_conv_q8_streamable, resblock_q8_streamable, stream_head_conv,
        stream_head_conv_q8, stream_resblock, stream_resblock_q8)
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 64, 96),
                          jnp.bfloat16)
    pc = init_conv(jax.random.PRNGKey(1), 3, 3, 96, 96)
    assert head_conv_q8_streamable(pc, x)
    pk, scale = stream_head_conv_q8(pc, x)
    ref = stream_head_conv(pc, x)
    ref_scale = feature_scale8(ref)
    assert np.asarray(scale).tobytes() == np.asarray(ref_scale).tobytes()
    assert np.asarray(pk).tobytes() == \
        np.asarray(quantize_pack_feature8(ref, ref_scale)).tobytes()

    xr = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 128, 32),
                           jnp.bfloat16)
    pr = init_residual_block(jax.random.PRNGKey(3), 32, 32, "instance",
                             stride=1)
    assert resblock_q8_streamable(pr, xr, "instance")
    pk_r, scale_r = stream_resblock_q8("instance", pr, xr)
    ref_r = stream_resblock("instance", pr, xr)
    rs = feature_scale8(ref_r)
    assert np.asarray(scale_r).tobytes() == np.asarray(rs).tobytes()
    assert np.asarray(pk_r).tobytes() == \
        np.asarray(quantize_pack_feature8(ref_r, rs)).tobytes()
    # Zero cotangent (bit-transport semantics).
    g = jax.grad(lambda x_: jnp.sum(stream_head_conv_q8(pc, x_)[0]
                                    .astype(jnp.float32)))(x)
    assert float(jnp.max(jnp.abs(g.astype(jnp.float32)))) == 0.0
    # Disarmed, the q8 gates must refuse — layout changes never engage
    # by default.
    monkeypatch.setenv("RAFT_LANE_PACK8", "0")
    assert not head_conv_q8_streamable(pc, x)
    assert not resblock_q8_streamable(pr, xr, "instance")


# ---------------------------------------------------------------------------
# End to end: the armed model path and its kill switch.


def _e2e_case(seed=0, hw=(64, 96)):
    cfg = RAFTStereoConfig(corr_implementation="reg_tpu",
                           mixed_precision=True)
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(seed)
    i1 = jnp.asarray(rng.uniform(0, 255, (1, *hw, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (1, *hw, 3)), jnp.float32)
    return cfg, params, i1, i2


def _packed_keys(state):
    """Carry keys holding lane containers ({"pk", "scale"} dicts)."""
    def has_pk(v):
        if isinstance(v, dict):
            return "pk" in v
        if isinstance(v, (list, tuple)):
            return any(has_pk(leaf) for leaf in v)
        return False
    return sorted(k for k, v in state.items() if has_pk(v))


def test_lane_armed_forward_equals_prepare_segments(monkeypatch):
    """Armed: one 4-iter forward == prepare + 2x 2-iter segments, bit for
    bit — the packed containers ride the carry and the segments consume
    them through the same producers the forward fake-quantized through
    (the anytime invariant every serving mode stands on)."""
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    cfg, params, i1, i2 = _e2e_case()
    low_ref, up_ref = raft_stereo_forward(params, cfg, i1, i2, iters=4,
                                          test_mode=True)
    state = raft_stereo_prepare(params, cfg, i1, i2)
    assert _packed_keys(state) == ["fmap1", "fmap2", "inp"]
    for _ in range(2):
        state, low, up = raft_stereo_segment(params, cfg, state, iters=2)
    assert np.asarray(up).tobytes() == np.asarray(up_ref).tobytes()
    assert np.asarray(low).tobytes() == np.asarray(low_ref).tobytes()


def test_lane_prepare_warm_consumes_packed_bitwise(monkeypatch):
    """Armed warm start: prepare_warm with zero flow is bitwise the cold
    prepare (packed container leaves INCLUDED), and the warm advance
    chain consumes the packed carry bit-identically to the cold chain."""
    from raft_stereo_tpu.serve.session import build_program
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    cfg, params, i1, i2 = _e2e_case(seed=7)
    f = cfg.downsample_factor
    zeros = jnp.zeros((1, i1.shape[1] // f, i1.shape[2] // f, 1),
                      jnp.float32)
    (cold,) = build_program("prepare", cfg, 0)(params, i1, i2)
    (warm,) = build_program("prepare_warm", cfg, 0)(params, i1, i2, zeros)
    flat_c, tree_c = jax.tree_util.tree_flatten(cold)
    flat_w, tree_w = jax.tree_util.tree_flatten(warm)
    assert tree_c == tree_w
    for a, b in zip(flat_c, flat_w):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    adv = build_program("advance", cfg, 2)
    sc, _, _ = adv(params, cold)
    sw, _, _ = adv(params, warm)
    for a, b in zip(jax.tree_util.tree_leaves(sc),
                    jax.tree_util.tree_leaves(sw)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_lane_default_off_byte_identity(monkeypatch):
    """RAFT_LANE_PACK8 unset and "0" are the SAME program: byte-identical
    outputs and a container-free carry (the kill-switch contract the
    breaker's lane_pack8 rung disengages through)."""
    cfg, params, i1, i2 = _e2e_case(seed=3)
    monkeypatch.delenv("RAFT_LANE_PACK8", raising=False)
    low_a, up_a = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                      test_mode=True)
    state = raft_stereo_prepare(params, cfg, i1, i2)
    assert _packed_keys(state) == []
    monkeypatch.setenv("RAFT_LANE_PACK8", "0")
    low_b, up_b = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                      test_mode=True)
    assert np.asarray(up_a).tobytes() == np.asarray(up_b).tobytes()
    assert np.asarray(low_a).tobytes() == np.asarray(low_b).tobytes()


def test_lane_train_mode_untouched(monkeypatch):
    """The packed context path is INFERENCE-ONLY by construction
    (``pack_ctx = test_mode and ...``): the training forward is bitwise
    unchanged by the switch — quantization never perturbs the train loss
    surface or its gradients."""
    cfg, params, i1, i2 = _e2e_case(seed=5, hw=(32, 64))
    monkeypatch.delenv("RAFT_LANE_PACK8", raising=False)
    preds_off = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                    test_mode=False)
    monkeypatch.setenv("RAFT_LANE_PACK8", "1")
    preds_on = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                   test_mode=False)
    for a, b in zip(jax.tree_util.tree_leaves(preds_off),
                    jax.tree_util.tree_leaves(preds_on)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

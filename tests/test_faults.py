"""Fault-injection recovery tests (DESIGN.md "Failure recovery").

Every recovery path of the reliability layer, driven end-to-end on CPU by
the deterministic harness in ``raft_stereo_tpu/faults.py`` — no env vars,
no wall-clock, no flakiness:

1. transient IO fault -> bounded retry -> training input bit-for-bit equal
   to the fault-free run;
2. permanently-corrupt sample -> quarantine + deterministic substitution +
   report; training completes;
3. injected NaN step -> params/opt_state untouched inside the compiled
   step, ``skipped_steps`` counted, N consecutive failures abort loudly;
4. truncated newest checkpoint -> auto-resume falls back to the previous
   valid bundle and continues the OneCycle schedule;
plus the SIGTERM preempt -> resume round trip over the same machinery.
"""

import os
import os.path as osp

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import TrainConfig
from raft_stereo_tpu.data.loader import StereoLoader
from raft_stereo_tpu.engine import checkpoint as ckpt
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_train_step
from raft_stereo_tpu.faults import (FaultPlan, FaultyDataset,
                                    poisoned_batches, truncate_file)
from raft_stereo_tpu.models import init_raft_stereo
from tests.test_eval_engine import TINY, _tiny_things_tree

pytestmark = pytest.mark.faults


class ToyDataset:
    """Deterministic dict-sample dataset matching the loader protocol."""

    def __init__(self, n=8):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, index, rng=None):
        v = rng.standard_normal(4).astype(np.float32) + index
        return {"image1": v, "image2": v, "flow": v[:1], "valid": v[:1]}


def _toy_loader(plan=None, retries=2, n=8, seed=7):
    ds = ToyDataset(n)
    if plan is not None:
        ds = FaultyDataset(ds, plan)
    return StereoLoader(ds, batch_size=4, num_workers=2, seed=seed,
                        retries=retries, retry_backoff=0.001)


def _epochs(loader, n=2):
    return [b["image1"].copy() for _ in range(n) for b in loader]


def _tcfg(**kw):
    base = dict(batch_size=1, image_size=(32, 48), train_iters=2,
                num_workers=1, spatial_scale=(-0.2, 0.4),
                data_retry_backoff=0.001)
    base.update(kw)
    return TrainConfig(**base)


def _leaves_equal(a, b):
    return all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def _adam_count(opt_state) -> int:
    # apply_if_finite(chain(clip, adamw)): inner_state[1] is the adamw chain
    # state, whose first element is ScaleByAdamState — its count is the
    # number of APPLIED updates, i.e. the OneCycle schedule position.
    return int(opt_state.inner_state[1][0].count)


# ---------------------------------------------------------------------------
# Path 1+2: data IO — retry, quarantine, substitution (loader level)
# ---------------------------------------------------------------------------

def test_transient_fault_retries_bit_identical():
    clean = _epochs(_toy_loader())
    loader = _toy_loader(FaultPlan(io_errors={3: 1}))  # fails once, then loads
    faulted = _epochs(loader)
    assert all((a == b).all() for a, b in zip(clean, faulted))
    assert loader.quarantine_report() == {}  # transient != quarantined


def test_permanent_fault_quarantined_substituted_and_deterministic():
    loader = _toy_loader(FaultPlan(io_errors={3: -1}))
    run1 = _epochs(loader)
    report = loader.quarantine_report()
    assert list(report) == [3] and "injected IO fault" in report[3]
    # Substitution is keyed off [seed, epoch, position]: independent runs
    # fill the bad slot with the identical substitute.
    run2 = _epochs(_toy_loader(FaultPlan(io_errors={3: -1})))
    assert all((a == b).all() for a, b in zip(run1, run2))
    # Only batches containing the bad sample differ from the clean run.
    clean = _epochs(_toy_loader())
    assert 0 < sum((a != b).any() for a, b in zip(clean, run1)) < len(clean)


def test_quarantined_sample_skips_retries_on_later_epochs():
    plan = FaultPlan(io_errors={3: -1})
    ds = FaultyDataset(ToyDataset(), plan)
    loader = StereoLoader(ds, batch_size=4, num_workers=1, seed=7,
                          retries=2, retry_backoff=0.001)
    _epochs(loader, n=1)
    attempts_epoch1 = ds.attempts[3]
    assert attempts_epoch1 == 3  # initial + 2 retries, then quarantined
    _epochs(loader, n=1)
    assert ds.attempts[3] == attempts_epoch1  # fast path: not re-probed


def test_no_loadable_substitute_raises():
    # Every sample is permanently bad: the loader must fail loudly, not spin.
    loader = _toy_loader(FaultPlan(io_errors={i: -1 for i in range(8)}))
    with pytest.raises(RuntimeError, match="substitute"):
        _epochs(loader, n=1)


def test_quarantine_cap_aborts_on_systematic_failure():
    # Isolated corruption is substituted; a failure rate past the cap
    # (1% of the dataset, floored at 16) is a pipeline bug and must abort
    # loudly instead of silently reshaping the training distribution.
    n = 4096
    bad = FaultPlan(io_errors={i: -1 for i in range(64)})
    loader = _toy_loader(bad, retries=0, n=n)
    with pytest.raises(RuntimeError, match="systematic"):
        _epochs(loader, n=1)


def test_corrupt_file_on_disk_quarantines(tmp_path):
    """Real decode path: a garbage PNG raises inside PIL and is quarantined."""
    from raft_stereo_tpu.data.datasets import SceneFlowDatasets
    root = _tiny_things_tree(tmp_path)
    bad = osp.join(root, "FlyingThings3D", "frames_cleanpass", "TRAIN", "A",
                   "0000", "left", "0006.png")
    with open(bad, "wb") as f:
        f.write(b"not a png at all")
    aug = {"crop_size": [32, 48], "min_scale": -0.2, "max_scale": 0.4,
           "do_flip": False, "yjitter": True}
    clean = SceneFlowDatasets(aug, root=root, dstype="frames_cleanpass")
    final = SceneFlowDatasets(aug, root=root, dstype="frames_finalpass")
    loader = StereoLoader(clean + final, batch_size=1, num_workers=1, seed=0,
                          retries=1, retry_backoff=0.001)
    batches = list(loader)
    assert len(batches) == 2
    assert loader.quarantine_report()  # the corrupt cleanpass sample


# ---------------------------------------------------------------------------
# Path 3: numerics — skip-if-nonfinite inside the compiled step
# ---------------------------------------------------------------------------

def test_nan_step_leaves_params_and_opt_state_unchanged():
    cfg = TINY
    params = jax.jit(lambda k: init_raft_stereo(k, cfg))(jax.random.PRNGKey(0))
    tx, _ = make_optimizer(2e-4, 100, skip_nonfinite=3)
    opt_state = jax.jit(tx.init)(params)
    step = make_train_step(cfg, tx, train_iters=2)
    batch = {"image1": jnp.zeros((1, 32, 48, 3)),
             "image2": jnp.zeros((1, 32, 48, 3)),
             "flow": jnp.zeros((1, 32, 48, 1)),
             "valid": jnp.ones((1, 32, 48))}

    params, opt_state, m = step(params, opt_state, batch)
    assert float(m["skipped"]) == 0.0 and float(m["finite"]) == 1.0
    p_before = jax.device_get(params)
    inner_before = jax.device_get(opt_state.inner_state)

    bad = dict(batch, image1=batch["image1"].at[0, 0, 0, 0].set(jnp.nan))
    params, opt_state, m = step(params, opt_state, bad)
    assert float(m["finite"]) == 0.0
    assert float(m["skipped"]) == 1.0
    assert float(m["notfinite_count"]) == 1.0
    # The rejected update leaves params and the inner optimizer state
    # (Adam moments, schedule count) bit-for-bit untouched.
    assert _leaves_equal(params, p_before)
    assert _leaves_equal(opt_state.inner_state, inner_before)

    # Consecutive counting, then reset on a finite step.
    params, opt_state, m = step(params, opt_state, bad)
    assert float(m["notfinite_count"]) == 2.0
    params, opt_state, m = step(params, opt_state, batch)
    assert float(m["skipped"]) == 0.0
    assert float(m["notfinite_count"]) == 0.0


# ---------------------------------------------------------------------------
# Path 4: checkpoint integrity (unit level)
# ---------------------------------------------------------------------------

def _toy_state():
    return {"w": jnp.arange(4, dtype=jnp.float32)}


def test_checkpoint_hash_detects_truncation_and_falls_back(tmp_path):
    d = str(tmp_path)
    p2 = ckpt.save_checkpoint(osp.join(d, "2_run.msgpack"), _toy_state(),
                              None, 2)
    p4 = ckpt.save_checkpoint(osp.join(d, "4_run.msgpack"), _toy_state(),
                              None, 4)
    assert ckpt.validate_checkpoint(p4)
    assert ckpt.find_latest_checkpoint(d) == p4
    truncate_file(p4)
    assert not ckpt.validate_checkpoint(p4)
    with pytest.raises(ckpt.CheckpointError):
        ckpt.load_checkpoint(p4, _toy_state(), None)
    # Fallback: the newest VALID bundle wins.
    assert ckpt.find_latest_checkpoint(d) == p2
    _, _, step = ckpt.load_checkpoint(p2, _toy_state(), None)
    assert step == 2
    truncate_file(p2, keep_bytes=4)  # not even a full header
    assert ckpt.find_latest_checkpoint(d) is None


def test_unwrapped_opt_state_restores_into_skip_wrapper(tmp_path):
    """Migration: a bundle saved WITHOUT apply_if_finite (pre-wrapper run or
    --max_bad_steps 0) restores into a wrapped optimizer — inner state kept,
    failure counters fresh — instead of a pytree-structure error."""
    import optax

    params = _toy_state()
    tx_plain, _ = make_optimizer(2e-4, 10, skip_nonfinite=0)
    plain = tx_plain.init(params)
    path = ckpt.save_checkpoint(osp.join(str(tmp_path), "5_m.msgpack"),
                                params, plain, 5)
    tx_wrapped, _ = make_optimizer(2e-4, 10, skip_nonfinite=3)
    template = tx_wrapped.init(params)
    _, restored, step = ckpt.load_checkpoint(path, params, template)
    assert step == 5
    assert isinstance(restored, optax.ApplyIfFiniteState)
    assert int(restored.notfinite_count) == 0
    assert _leaves_equal(restored.inner_state, plain)


def test_wrapped_opt_state_restores_into_plain_optimizer(tmp_path):
    """Reverse migration: a bundle saved WITH apply_if_finite (the default)
    restores into an unwrapped optimizer (--max_bad_steps 0) by taking its
    inner state."""
    params = _toy_state()
    tx_w, _ = make_optimizer(2e-4, 10, skip_nonfinite=3)
    wrapped = tx_w.init(params)
    path = ckpt.save_checkpoint(osp.join(str(tmp_path), "7_w.msgpack"),
                                params, wrapped, 7)
    tx_p, _ = make_optimizer(2e-4, 10, skip_nonfinite=0)
    _, restored, step = ckpt.load_checkpoint(path, params, tx_p.init(params))
    assert step == 7
    assert _leaves_equal(restored, wrapped.inner_state)


def test_run_name_grammar_guard():
    # Names that parse as another run's numbered/marker bundles would cause
    # silent cross-run prune/resume interference; reject them up front.
    with pytest.raises(ValueError, match="grammar"):
        ckpt.check_run_name("2_foo")
    with pytest.raises(ValueError, match="grammar"):
        ckpt.check_run_name("epoch_v2")
    with pytest.raises(ValueError, match="grammar"):
        ckpt.check_run_name("preempt_x")
    assert ckpt.check_run_name("raft-stereo") == "raft-stereo"


def test_legacy_headerless_checkpoint_loads(tmp_path):
    from flax import serialization
    path = osp.join(str(tmp_path), "3_old.msgpack")
    blob = serialization.to_bytes(
        {"params": jax.device_get(_toy_state()), "opt_state": None, "step": 3})
    with open(path, "wb") as f:
        f.write(blob)
    assert ckpt.validate_checkpoint(path)
    params, _, step = ckpt.load_checkpoint(path, _toy_state(), None)
    assert step == 3
    np.testing.assert_array_equal(np.asarray(params["w"]), np.arange(4))
    assert ckpt.find_latest_checkpoint(str(tmp_path)) == path


def test_prune_checkpoints_keep_last_k(tmp_path):
    d = str(tmp_path)
    for s in (2, 4, 6, 8):
        ckpt.save_checkpoint(osp.join(d, f"{s}_run.msgpack"), _toy_state(),
                             None, s)
    # preempt/epoch/final bundles are retention-exempt
    ckpt.save_checkpoint(osp.join(d, "5_preempt_run.msgpack"), _toy_state(),
                         None, 5)
    ckpt.save_checkpoint(osp.join(d, "3_epoch_run.msgpack"), _toy_state(),
                         None, 3)
    ckpt.save_checkpoint(osp.join(d, "run.msgpack"), _toy_state(), None, 8)
    removed = ckpt.prune_checkpoints(d, "run", keep=2)
    assert sorted(osp.basename(p) for p in removed) == ["2_run.msgpack",
                                                        "4_run.msgpack"]
    assert sorted(os.listdir(d)) == ["3_epoch_run.msgpack", "5_preempt_run.msgpack",
                                     "6_run.msgpack", "8_run.msgpack",
                                     "run.msgpack"]


def test_prune_never_deletes_the_last_valid_fallbacks(tmp_path):
    """Corrupt bundles must not count toward keep-last-K: with the newest
    K periodic saves corrupted on disk, pruning has to retain the older
    valid ones find_latest_checkpoint will fall back to."""
    d = str(tmp_path)
    for s in (2, 4, 6, 8):
        ckpt.save_checkpoint(osp.join(d, f"{s}_run.msgpack"), _toy_state(),
                             None, s)
    truncate_file(osp.join(d, "6_run.msgpack"))
    truncate_file(osp.join(d, "8_run.msgpack"))
    removed = ckpt.prune_checkpoints(d, "run", keep=2)
    # 2_run and 4_run are the only valid bundles left: nothing is removable,
    # and the corrupt ones inside the window are left in place.
    assert removed == []
    assert ckpt.find_latest_checkpoint(d) == osp.join(d, "4_run.msgpack")
    # Once enough newer VALID bundles exist again, older ones (corrupt or
    # not) age out normally.
    for s in (10, 12):
        ckpt.save_checkpoint(osp.join(d, f"{s}_run.msgpack"), _toy_state(),
                             None, s)
    removed = ckpt.prune_checkpoints(d, "run", keep=2)
    assert sorted(osp.basename(p) for p in removed) == [
        "2_run.msgpack", "4_run.msgpack", "6_run.msgpack", "8_run.msgpack"]


def test_poisoned_batches_targets_exact_step():
    batches = [{"image1": np.zeros((1, 2, 2, 3), np.float32)}
               for _ in range(4)]
    out = list(poisoned_batches(iter(batches), FaultPlan(nan_at_steps=(6,)),
                                start_step=5))
    assert not np.isnan(out[0]["image1"]).any()
    assert np.isnan(out[1]["image1"][0, 0, 0, 0])
    assert not np.isnan(out[2]["image1"]).any()
    # source batches are never mutated in place
    assert not np.isnan(batches[1]["image1"]).any()


# ---------------------------------------------------------------------------
# End-to-end train-loop recovery (tiny real model; one compile per train())
# ---------------------------------------------------------------------------

def test_train_skips_nan_quarantines_and_retains(tmp_path, monkeypatch):
    """One training run exercising three recovery paths at once: a NaN step
    is skipped (not fatal), a corrupt PNG is quarantined and substituted,
    and periodic checkpoints honor keep-last-K retention."""
    from raft_stereo_tpu.engine.train import train

    root = _tiny_things_tree(tmp_path)
    bad = osp.join(root, "FlyingThings3D", "frames_finalpass", "TRAIN", "A",
                   "0000", "left", "0006.png")
    with open(bad, "wb") as f:
        f.write(b"garbage")
    monkeypatch.chdir(tmp_path)
    tcfg = _tcfg(name="ft", num_steps=8, ckpt_every=2, keep_ckpts=2,
                 max_bad_steps=3, data_retries=1)
    res = train(TINY, tcfg, data_root=root, validate=False,
                faults=FaultPlan(nan_at_steps=(1,)))

    assert res["skipped_steps"] == 1.0
    assert res["quarantined_samples"] >= 1.0
    # keep-last-K over the periodic saves (4 written at 2/4/6/8, 2 kept).
    periodic = sorted(f for f in os.listdir("checkpoints")
                      if f.endswith("_ft.msgpack"))
    assert periodic == ["6_ft.msgpack", "8_ft.msgpack"]
    # Final state: 8 steps, one skipped -> 7 applied updates; the schedule
    # position (Adam count) reflects exactly the applied ones.
    params = init_raft_stereo(jax.random.PRNGKey(0), TINY)
    tx, _ = make_optimizer(tcfg.lr, tcfg.num_steps,
                           skip_nonfinite=tcfg.max_bad_steps)
    _, opt_state, step = ckpt.load_checkpoint("checkpoints/ft.msgpack",
                                              params, tx.init(params))
    assert step == 8
    assert _adam_count(opt_state) == 7

    # Auto-resume of the finished schedule (newest numbered bundle at
    # num_steps) must train ZERO extra steps — the horizon guard fires
    # before the loop, not after an off-schedule step already ran.
    res2 = train(TINY, _tcfg(name="ft", num_steps=8, ckpt_every=2,
                             keep_ckpts=2, max_bad_steps=3, data_retries=1,
                             restore_ckpt="checkpoints"),
                 data_root=root, validate=False)
    assert res2["skipped_steps"] == 0.0
    _, opt_state, step = ckpt.load_checkpoint("checkpoints/ft.msgpack",
                                              params, tx.init(params))
    assert step == 8
    assert _adam_count(opt_state) == 7


def test_train_aborts_after_consecutive_nans(tmp_path, monkeypatch):
    from raft_stereo_tpu.engine.train import train

    root = _tiny_things_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    tcfg = _tcfg(name="abort", num_steps=8, ckpt_every=100, max_bad_steps=2)
    with pytest.raises(FloatingPointError, match="2 consecutive"):
        train(TINY, tcfg, data_root=root, validate=False,
              faults=FaultPlan(nan_at_steps=(0, 1, 2)))
    # An aborted run must not masquerade as a finished one.
    assert not osp.exists("checkpoints/abort.msgpack")


def test_preempt_resume_roundtrip_continues_schedule(tmp_path, monkeypatch):
    """SIGTERM mid-run -> preempt checkpoint -> auto-resume from the
    checkpoint DIRECTORY continues the OneCycle schedule from that step."""
    from raft_stereo_tpu.engine.train import train

    root = _tiny_things_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    tcfg = _tcfg(name="pre", num_steps=10, ckpt_every=100)
    train(TINY, tcfg, data_root=root, validate=False,
          faults=FaultPlan(sigterm_at_step=2))
    files = os.listdir("checkpoints")
    assert "2_preempt_pre.msgpack" in files
    assert "pre.msgpack" not in files  # preempted != finished

    # Relaunch with the same flags (same name), pointing at the checkpoint
    # directory: auto-resume picks up this run's preempt bundle.
    tcfg2 = _tcfg(name="pre", num_steps=4, ckpt_every=100,
                  restore_ckpt="checkpoints")
    train(TINY, tcfg2, data_root=root, validate=False)
    params = init_raft_stereo(jax.random.PRNGKey(0), TINY)
    tx, _ = make_optimizer(tcfg2.lr, tcfg2.num_steps,
                           skip_nonfinite=tcfg2.max_bad_steps)
    _, opt_state, step = ckpt.load_checkpoint("checkpoints/pre.msgpack",
                                              params, tx.init(params))
    assert step == 4
    # 2 applied updates before preemption + 2 after resume: the schedule
    # continued instead of restarting (a fresh run would also show 4 only
    # if it ran 4 updates from step 0 — the preempt bundle at step 2 plus
    # this count pins the resume point).
    assert _adam_count(opt_state) == 4


def test_resume_falls_back_past_truncated_newest(tmp_path, monkeypatch,
                                                 caplog):
    """Acceptance path 4 end-to-end: the newest bundle in the resume
    directory is truncated; auto-resume logs it, restores the previous
    valid bundle, and finishes the schedule."""
    import logging

    from raft_stereo_tpu.engine.train import train

    root = _tiny_things_tree(tmp_path)
    monkeypatch.chdir(tmp_path)
    train(TINY, _tcfg(name="a", num_steps=2, ckpt_every=1, keep_ckpts=0),
          data_root=root, validate=False)
    assert ckpt.find_latest_checkpoint("checkpoints") == \
        osp.join("checkpoints", "2_a.msgpack")
    truncate_file("checkpoints/2_a.msgpack")

    with caplog.at_level(logging.WARNING,
                         logger="raft_stereo_tpu.engine.checkpoint"):
        assert ckpt.find_latest_checkpoint("checkpoints") == \
            osp.join("checkpoints", "1_a.msgpack")
        train(TINY, _tcfg(name="a", num_steps=3, ckpt_every=100,
                          restore_ckpt="checkpoints"),
              data_root=root, validate=False)
    assert "skipping invalid checkpoint" in caplog.text
    params = init_raft_stereo(jax.random.PRNGKey(0), TINY)
    _, _, step = ckpt.load_checkpoint("checkpoints/a.msgpack", params, None)
    assert step == 3

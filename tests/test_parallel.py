"""Multi-device tests on the 8-device virtual-CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_eval_step, make_train_step
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.parallel import make_mesh, shard_batch


def _batch(rng, b, h, w):
    return {
        "image1": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)),
        "image2": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)),
        "flow": jnp.asarray(rng.standard_normal((b, h, w, 1)).astype(np.float32)),
        "valid": jnp.ones((b, h, w), jnp.float32),
    }


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh(n_data=4, n_space=2)
    assert mesh2.shape == {"data": 4, "space": 2}


def test_data_parallel_train_step_runs_and_matches_single(rng):
    cfg = RAFTStereoConfig(n_gru_layers=2)
    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 8, 32, 64)

    mesh = make_mesh(n_data=8)
    step_dp = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
    p_dp, s_dp, m_dp = step_dp(jax.tree.map(jnp.copy, params), tx.init(params),
                               shard_batch(batch, mesh))

    step_1 = make_train_step(cfg, tx, train_iters=2)
    p_1, s_1, m_1 = step_1(jax.tree.map(jnp.copy, params), tx.init(params), batch)

    # Data-parallel execution must be semantically identical to single-device.
    np.testing.assert_allclose(float(m_dp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_spatial_sharded_eval_matches_single(rng):
    """H sharded over the ``space`` axis must be numerically identical.

    This is the full-resolution enabler: the (B, H, W1, W2) corr volume —
    the memory hog at Middlebury-F — lives 1/n_space per device; XLA
    supplies the conv halo exchanges. Verified against the unsharded
    program, and the per-device peak is checked to actually shrink.
    """
    cfg = RAFTStereoConfig(n_gru_layers=2)
    params = init_raft_stereo(jax.random.key(0), cfg)
    batch = _batch(rng, 1, 64, 64)

    mesh = make_mesh(n_data=1, n_space=8)
    step_sp = make_eval_step(cfg, valid_iters=2, mesh=mesh)
    _, up_sp = step_sp(params, *shard_batch(
        [batch["image1"], batch["image2"]], mesh, spatial=True))

    step_1 = make_eval_step(cfg, valid_iters=2)
    _, up_1 = step_1(params, batch["image1"], batch["image2"])

    np.testing.assert_allclose(np.asarray(up_sp), np.asarray(up_1), atol=2e-3)

    # The sharded program's per-device footprint must be a fraction of the
    # replicated one (the corr volume + activations split along H). Checked
    # at a taller shape: below ~80 MB of live temps a fixed allocator floor
    # (~15 MB on the CPU backend) hides the split (measured 64x64: ratio
    # 0.96 vs 256x128: 0.22).
    def peak(step, args):
        lowered = step.lower(params, *args)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    big = _batch(rng, 1, 256, 128)
    sharded = peak(step_sp, shard_batch(
        [big["image1"], big["image2"]], mesh, spatial=True))
    single = peak(step_1, [big["image1"], big["image2"]])
    assert sharded < single / 2, (sharded, single)


def test_choose_mesh_topologies():
    """Training mesh selection from (batch, spatial_shard, devices, procs)."""
    from raft_stereo_tpu.engine.train import choose_mesh

    dev = jax.devices()  # 8 virtual CPU devices (conftest)
    m = choose_mesh(8, 1, dev, 1)
    assert dict(m.shape) == {"data": 8, "space": 1}
    m = choose_mesh(2, 4, dev, 1)  # big-crop mode: 2-way data x 4-way height
    assert dict(m.shape) == {"data": 2, "space": 4}
    m = choose_mesh(6, 1, dev, 1)  # largest batch divisor <= devices
    assert dict(m.shape) == {"data": 6, "space": 1}
    assert choose_mesh(1, 1, dev[:1], 1) is None  # single device: no mesh
    m = choose_mesh(8, 1, dev, 2)  # pod: all devices, batch must divide
    assert dict(m.shape) == {"data": 8, "space": 1}
    # pod of 2 hosts x 4 local devices: space axis must stay within a host
    m = choose_mesh(2, 4, dev, 2, local_device_count=4)
    assert dict(m.shape) == {"data": 2, "space": 4}

    with pytest.raises(ValueError, match="divide 32"):
        choose_mesh(8, 3, dev[:6], 1)
    with pytest.raises(ValueError, match="does not divide"):
        choose_mesh(8, 16, dev, 1)
    with pytest.raises(ValueError, match="divide evenly"):
        choose_mesh(5, 1, dev, 2)
    with pytest.raises(ValueError, match="ICI"):
        choose_mesh(1, 8, dev, 2, local_device_count=4)


def test_spatial_sharded_train_step_matches_single(rng):
    """Grads/updates under a (data=2, space=4) mesh match single-device."""
    cfg = RAFTStereoConfig(n_gru_layers=1)
    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 2, 64, 64)

    mesh = make_mesh(n_data=2, n_space=4)
    step_sp = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
    p_sp, _, m_sp = step_sp(jax.tree.map(jnp.copy, params), tx.init(params),
                            shard_batch(batch, mesh, spatial=True))

    step_1 = make_train_step(cfg, tx, train_iters=2)
    p_1, _, m_1 = step_1(jax.tree.map(jnp.copy, params), tx.init(params),
                         batch)

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_spatial_train_step_strips_pallas_kernels(rng):
    """ADVICE r3 (medium): a spatially-sharded TRAIN step with
    fused_update/reg_tpu requested must strip the Pallas kernels exactly
    like the eval path. The stripping is asserted directly on the shared
    guard (running the step alone proves nothing — interpret-mode Pallas
    happens to partition on the CPU mesh, unlike compiled Mosaic), then the
    stripped step is run end-to-end."""
    from raft_stereo_tpu.parallel.mesh import mesh_config_overrides
    cfg = RAFTStereoConfig(n_gru_layers=1, fused_update=True,
                           corr_implementation="reg_tpu",
                           mixed_precision=True)
    mesh = make_mesh(n_data=1, n_space=8)
    assert mesh_config_overrides(cfg, mesh) == {
        "fused_update": False, "corr_implementation": "reg"}
    assert mesh_config_overrides(cfg, None) == {}
    assert mesh_config_overrides(cfg, make_mesh(n_data=8, n_space=1)) == {}

    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 1, 64, 64)
    step = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
    _, _, metrics = step(jax.tree.map(jnp.copy, params), tx.init(params),
                         shard_batch(batch, mesh, spatial=True))
    assert np.isfinite(float(metrics["loss"]))


def test_eval_step_sharded(rng):
    cfg = RAFTStereoConfig(n_gru_layers=1)
    params = init_raft_stereo(jax.random.key(0), cfg)
    mesh = make_mesh(n_data=8)
    eval_step = make_eval_step(cfg, valid_iters=2, mesh=mesh)
    batch = _batch(rng, 8, 32, 64)
    flow_lr, flow_up = eval_step(params, batch["image1"], batch["image2"])
    assert flow_up.shape == (8, 32, 64, 1)
    assert np.isfinite(np.asarray(flow_up)).all()

"""Multi-device tests on the 8-device virtual-CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.engine.optimizer import make_optimizer
from raft_stereo_tpu.engine.steps import make_eval_step, make_train_step
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.ops.jax_compat import modern_jax
from raft_stereo_tpu.parallel import make_mesh, shard_batch

# Old-JAX XLA:CPU hard-crashes (SIGSEGV, not an exception) compiling
# custom-partitioned Pallas programs under a mesh; the single-device
# compat shims (ops/jax_compat.py) cover everything else. These paths
# are certified on the modern-JAX TPU host.
requires_partitionable_kernels = pytest.mark.skipif(
    not modern_jax(),
    reason="custom-partitioned Pallas under a mesh segfaults old XLA:CPU")


def _batch(rng, b, h, w):
    return {
        "image1": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)),
        "image2": jnp.asarray(rng.uniform(0, 255, (b, h, w, 3)).astype(np.float32)),
        "flow": jnp.asarray(rng.standard_normal((b, h, w, 1)).astype(np.float32)),
        "valid": jnp.ones((b, h, w), jnp.float32),
    }


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh(n_data=4, n_space=2)
    assert mesh2.shape == {"data": 4, "space": 2}


def test_data_parallel_train_step_runs_and_matches_single(rng):
    cfg = RAFTStereoConfig(n_gru_layers=2)
    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 8, 32, 64)

    mesh = make_mesh(n_data=8)
    step_dp = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
    p_dp, s_dp, m_dp = step_dp(jax.tree.map(jnp.copy, params), tx.init(params),
                               shard_batch(batch, mesh))

    step_1 = make_train_step(cfg, tx, train_iters=2)
    p_1, s_1, m_1 = step_1(jax.tree.map(jnp.copy, params), tx.init(params), batch)

    # Data-parallel execution must be semantically identical to single-device.
    np.testing.assert_allclose(float(m_dp["loss"]), float(m_1["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_spatial_sharded_eval_matches_single(rng):
    """H sharded over the ``space`` axis must be numerically identical.

    This is the full-resolution enabler: the (B, H, W1, W2) corr volume —
    the memory hog at Middlebury-F — lives 1/n_space per device; XLA
    supplies the conv halo exchanges. Verified against the unsharded
    program, and the per-device peak is checked to actually shrink.
    """
    cfg = RAFTStereoConfig(n_gru_layers=2)
    params = init_raft_stereo(jax.random.key(0), cfg)
    batch = _batch(rng, 1, 64, 64)

    mesh = make_mesh(n_data=1, n_space=8)
    step_sp = make_eval_step(cfg, valid_iters=2, mesh=mesh)
    _, up_sp = step_sp(params, *shard_batch(
        [batch["image1"], batch["image2"]], mesh, spatial=True))

    step_1 = make_eval_step(cfg, valid_iters=2)
    _, up_1 = step_1(params, batch["image1"], batch["image2"])

    np.testing.assert_allclose(np.asarray(up_sp), np.asarray(up_1), atol=2e-3)

    # The sharded program's per-device footprint must be a fraction of the
    # replicated one (the corr volume + activations split along H). Checked
    # at a taller shape: below ~80 MB of live temps a fixed allocator floor
    # (~15 MB on the CPU backend) hides the split (measured 64x64: ratio
    # 0.96 vs 256x128: 0.22).
    def peak(step, args):
        lowered = step.lower(params, *args)
        return lowered.compile().memory_analysis().temp_size_in_bytes

    big = _batch(rng, 1, 256, 128)
    sharded = peak(step_sp, shard_batch(
        [big["image1"], big["image2"]], mesh, spatial=True))
    single = peak(step_1, [big["image1"], big["image2"]])
    assert sharded < single / 2, (sharded, single)


def test_choose_mesh_topologies():
    """Training mesh selection from (batch, spatial_shard, devices, procs)."""
    from raft_stereo_tpu.engine.train import choose_mesh

    dev = jax.devices()  # 8 virtual CPU devices (conftest)
    m = choose_mesh(8, 1, dev, 1)
    assert dict(m.shape) == {"data": 8, "space": 1}
    m = choose_mesh(2, 4, dev, 1)  # big-crop mode: 2-way data x 4-way height
    assert dict(m.shape) == {"data": 2, "space": 4}
    m = choose_mesh(6, 1, dev, 1)  # largest batch divisor <= devices
    assert dict(m.shape) == {"data": 6, "space": 1}
    assert choose_mesh(1, 1, dev[:1], 1) is None  # single device: no mesh
    m = choose_mesh(8, 1, dev, 2)  # pod: all devices, batch must divide
    assert dict(m.shape) == {"data": 8, "space": 1}
    # pod of 2 hosts x 4 local devices: space axis must stay within a host
    m = choose_mesh(2, 4, dev, 2, local_device_count=4)
    assert dict(m.shape) == {"data": 2, "space": 4}

    with pytest.raises(ValueError, match="divide 32"):
        choose_mesh(8, 3, dev[:6], 1)
    with pytest.raises(ValueError, match="does not divide"):
        choose_mesh(8, 16, dev, 1)
    with pytest.raises(ValueError, match="divide evenly"):
        choose_mesh(5, 1, dev, 2)
    with pytest.raises(ValueError, match="ICI"):
        choose_mesh(1, 8, dev, 2, local_device_count=4)


def test_spatial_sharded_train_step_matches_single(rng):
    """Grads/updates under a (data=2, space=4) mesh match single-device."""
    cfg = RAFTStereoConfig(n_gru_layers=1)
    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 2, 64, 64)

    mesh = make_mesh(n_data=2, n_space=4)
    step_sp = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
    p_sp, _, m_sp = step_sp(jax.tree.map(jnp.copy, params), tx.init(params),
                            shard_batch(batch, mesh, spatial=True))

    step_1 = make_train_step(cfg, tx, train_iters=2)
    p_1, _, m_1 = step_1(jax.tree.map(jnp.copy, params), tx.init(params),
                         batch)

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_1["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@requires_partitionable_kernels
def test_spatial_fused_train_step_runs(rng):
    """A spatially-sharded TRAIN step accepts fused_update untouched
    (r4): no config is stripped any more — mesh_config_overrides is
    empty by design — and the step matches single-device. (In train
    mode the scan body itself stays on the partitionable XLA chain:
    the kernels are test-mode-only by measurement, see
    raft_stereo_forward; the halo-exchange kernel path under this mesh
    is covered by test_spatial_sharded_fused_eval_matches_single.)"""
    import raft_stereo_tpu.ops.pallas_stream as ps
    from raft_stereo_tpu.parallel.mesh import mesh_config_overrides
    cfg = RAFTStereoConfig(n_gru_layers=1, fused_update=True,
                           corr_implementation="reg_tpu")
    mesh = make_mesh(n_data=1, n_space=8)
    assert mesh_config_overrides(cfg, mesh) == {}
    assert mesh_config_overrides(cfg, make_mesh(n_data=8, n_space=1)) == {}

    params = init_raft_stereo(jax.random.key(0), cfg)
    tx, _ = make_optimizer(lr=1e-4, num_steps=100)
    batch = _batch(rng, 1, 128, 64)
    old = ps.FORCE_FUSABLE_DTYPE
    ps.FORCE_FUSABLE_DTYPE = True
    try:
        step = make_train_step(cfg, tx, train_iters=2, mesh=mesh)
        p_sp, _, metrics = step(jax.tree.map(jnp.copy, params),
                                tx.init(params),
                                shard_batch(batch, mesh, spatial=True))
        step_1 = make_train_step(cfg, tx, train_iters=2)
        p_1, _, m_1 = step_1(jax.tree.map(jnp.copy, params),
                             tx.init(params), batch)
    finally:
        ps.FORCE_FUSABLE_DTYPE = old
    np.testing.assert_allclose(float(metrics["loss"]), float(m_1["loss"]),
                               rtol=1e-3)
    for a, b in zip(jax.tree.leaves(p_sp), jax.tree.leaves(p_1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@requires_partitionable_kernels
def test_spatial_sharded_fused_eval_matches_single(rng):
    """fused_update SURVIVES space>1 (VERDICT r3 #2, the r3 perf cliff):
    the streaming GRU/motion kernels run per-shard behind a ppermute
    halo exchange (ops/pallas_stream.py spatial variants). Equality with
    the unsharded fused run, within the same reassociation envelope the
    XLA spatial path has (test_spatial_sharded_eval_matches_single)."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    cfg = RAFTStereoConfig(n_gru_layers=3, corr_implementation="reg_tpu",
                           fused_update=True)
    params = init_raft_stereo(jax.random.key(0), cfg)
    batch = _batch(rng, 1, 128, 64)

    old = ps.FORCE_FUSABLE_DTYPE
    ps.FORCE_FUSABLE_DTYPE = True  # engage the kernels in fp32 interpret
    try:
        mesh = make_mesh(n_data=1, n_space=8)
        step_sp = make_eval_step(cfg, valid_iters=3, mesh=mesh)
        _, up_sp = step_sp(params, *shard_batch(
            [batch["image1"], batch["image2"]], mesh, spatial=True))
        step_1 = make_eval_step(cfg, valid_iters=3)
        _, up_1 = step_1(params, batch["image1"], batch["image2"])
    finally:
        ps.FORCE_FUSABLE_DTYPE = old
    np.testing.assert_allclose(np.asarray(up_sp), np.asarray(up_1),
                               atol=5e-3)


@requires_partitionable_kernels
@pytest.mark.parametrize("impl", ["reg_tpu", "alt_tpu"])
@pytest.mark.parametrize("n_data,n_space", [(8, 1), (2, 4), (1, 8)])
def test_partitioned_corr_kernels_match_reg(rng, impl, n_data, n_space):
    """The Pallas correlation kernels run UNDER the mesh (VERDICT r3 #2):
    equality with the XLA ``reg`` oracle for data-only, mixed and
    space-only shardings, with zero collectives in the compiled program
    (the custom_partitioning row rule splits them; nothing is gathered).

    Interpret mode on CPU pins the partitioning semantics; the kernel
    body itself is oracled on-chip by tests/test_corr_tpu.py."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from raft_stereo_tpu.corr import make_corr_fn

    b, h, w, d = 8, 16, 32, 16
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d)).astype(np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d)).astype(np.float32))
    coords = jnp.asarray(
        rng.uniform(-3, w + 3, (b, h, w)).astype(np.float32))
    ref = make_corr_fn("reg", f1, f2, num_levels=4, radius=4)(coords)

    mesh = make_mesh(n_data=n_data, n_space=n_space)
    sh = NamedSharding(mesh, P("data", "space"))

    def fwd(f1, f2, c):
        return make_corr_fn(impl, f1, f2, num_levels=4, radius=4)(c)

    jf = jax.jit(fwd, in_shardings=(sh, sh, sh), out_shardings=sh)
    args = [jax.device_put(x, sh) for x in (f1, f2, coords)]
    out = jf(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    txt = jf.lower(*args).compile().as_text()
    assert "all-gather" not in txt and "all-reduce" not in txt

    # Gradients flow per-shard through the custom_vjp too.
    def loss(f1, f2, c):
        return jnp.sum(fwd(f1, f2, c) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1)),
                in_shardings=(sh, sh, sh))(*args)
    g_ref = jax.grad(
        lambda a, b2: jnp.sum(
            make_corr_fn("reg", a, b2, num_levels=4, radius=4)(coords) ** 2),
        argnums=(0, 1))(f1, f2)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(g_ref[1]),
                               atol=2e-4)


def test_eval_step_sharded(rng):
    cfg = RAFTStereoConfig(n_gru_layers=1)
    params = init_raft_stereo(jax.random.key(0), cfg)
    mesh = make_mesh(n_data=8)
    eval_step = make_eval_step(cfg, valid_iters=2, mesh=mesh)
    batch = _batch(rng, 8, 32, 64)
    flow_lr, flow_up = eval_step(params, batch["image1"], batch["image2"])
    assert flow_up.shape == (8, 32, 64, 1)
    assert np.isfinite(np.asarray(flow_up)).all()

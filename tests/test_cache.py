"""graftrecall battery: exact-hit bitwise parity and the
zero-device-seconds reconciliation, fingerprint-change invalidation,
tenant isolation + own-LRU sub-caps, TTL expiry under FakeClock, byte-cap
accounting (eviction-to-zero gauge), near-tier semantics (tolerance=0
fully disabled; warm:cache:k labels with honest iteration counts), the
churn-storm bound (bytes + /metrics provably flat), drain drop
semantics, and the RAFT_CACHE_DIR disk spill.

Everything runs on CPU with the tiny model; FakeClock drives TTL math
deterministically.  The cache is LIBRARY-default OFF, so every service
here arms it explicitly — the same opt-in every other test rig gets by
NOT arming it.
"""

import os

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.serve import (InferenceSession, ResponseCache,
                                   ServiceConfig, SessionConfig,
                                   StereoService)
from raft_stereo_tpu.serve.cache import (block_signature,
                                         resolve_cache_bytes,
                                         resolve_cache_dir,
                                         resolve_cache_near_tol,
                                         resolve_cache_ttl_ms,
                                         signature_distance)

pytestmark = pytest.mark.cache

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # not multiples of 32: padding really engages


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


def make_pair(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (1, H, W, 3)).astype(np.float32))


def perturb(img, seed=1, sigma=2.0):
    rng = np.random.default_rng(seed)
    return np.clip(img + rng.normal(0, sigma, img.shape),
                   0, 255).astype(np.float32)


def make_service(params, cfg, *, clock=None, plan=None, max_batch=1,
                 cache_bytes=64 << 20, **svc_kw):
    session = InferenceSession(
        params, cfg,
        SessionConfig(valid_iters=4, segments=2, max_batch=max_batch,
                      canary=False,
                      batch_buckets=(1, max_batch) if max_batch > 1
                      else ()),
        clock=clock or FakeClock(), fault_plan=plan)
    return StereoService(session, ServiceConfig(
        max_queue=16, cache_bytes=cache_bytes, **svc_kw))


def request(left, right, rid=None, tenant=None, **kw):
    req = {"id": rid, "left": left.copy(), "right": right.copy()}
    if tenant is not None:
        req["tenant"] = tenant
    req.update(kw)
    return req


# ---------------------------------------------------------------------------
# Knob resolution: named errors, defaults, library-off default.
# ---------------------------------------------------------------------------


def test_knob_resolution_named_errors(monkeypatch):
    monkeypatch.delenv("RAFT_CACHE_BYTES", raising=False)
    assert resolve_cache_bytes() == 0  # library default: disabled
    assert resolve_cache_bytes(123) == 123
    monkeypatch.setenv("RAFT_CACHE_BYTES", "1024")
    assert resolve_cache_bytes() == 1024
    monkeypatch.setenv("RAFT_CACHE_BYTES", "-1")
    with pytest.raises(ValueError, match="RAFT_CACHE_BYTES"):
        resolve_cache_bytes()
    monkeypatch.setenv("RAFT_CACHE_BYTES", "zonk")
    with pytest.raises(ValueError, match="RAFT_CACHE_BYTES"):
        resolve_cache_bytes()
    monkeypatch.setenv("RAFT_CACHE_TTL_MS", "0")
    with pytest.raises(ValueError, match="RAFT_CACHE_TTL_MS"):
        resolve_cache_ttl_ms()
    monkeypatch.delenv("RAFT_CACHE_TTL_MS", raising=False)
    assert resolve_cache_ttl_ms() == pytest.approx(600_000.0)
    monkeypatch.setenv("RAFT_CACHE_NEAR_TOL", "-0.5")
    with pytest.raises(ValueError, match="RAFT_CACHE_NEAR_TOL"):
        resolve_cache_near_tol()
    monkeypatch.delenv("RAFT_CACHE_NEAR_TOL", raising=False)
    assert resolve_cache_near_tol() == 0.0
    monkeypatch.delenv("RAFT_CACHE_DIR", raising=False)
    assert resolve_cache_dir() is None
    monkeypatch.setenv("RAFT_CACHE_DIR", "/tmp/x")
    assert resolve_cache_dir() == "/tmp/x"


def test_disabled_cache_is_inert(tiny_params, tiny_cfg):
    """cache_bytes=0 (the ServiceConfig default): no key stamping, no
    counters, identical serving behavior — the whole pre-r18 stack."""
    svc = make_service(tiny_params, tiny_cfg, cache_bytes=0)
    la, ra = make_pair(0)
    req = request(la, ra, rid="x")
    r1 = svc.handle(req)
    r2 = svc.handle(request(la, ra, rid="y"))
    assert r1["quality"] == "full" and r2["quality"] == "full"
    assert "_cache_key" not in req
    assert not svc.cache.enabled
    assert int(svc.registry.value("raft_cache_misses_total")) == 0


# ---------------------------------------------------------------------------
# Exact tier: bitwise parity, zero device seconds, invalidation,
# isolation.
# ---------------------------------------------------------------------------


def test_exact_hit_bitwise_and_zero_device_seconds(tiny_params, tiny_cfg):
    """The two acceptance pins in one deterministic run: an exact hit is
    byte-identical to the cold-computed response AND moves NO device
    second anywhere — program counters, per-tenant usage nanoseconds and
    the tick deck all read exactly what they read before the hit (the
    PR 12 three-way reconciliation delta == 0).  Non-vacuous: injected
    slow forwards make every steady compute provably move them."""
    clock = FakeClock()
    plan = ServeFaultPlan(slow_forwards={i: 0.5 for i in range(64)})
    svc = make_service(tiny_params, tiny_cfg, clock=clock, plan=plan)
    la, ra = make_pair(0)
    lb, rb = make_pair(1)
    svc.handle(request(lb, rb, rid="warmup"))       # pays the compile
    cold = svc.handle(request(la, ra, rid="cold"))  # steady compute
    assert cold["status"] == "ok" and cold["quality"] == "full"
    reg = svc.registry

    def dev_total():
        return sum(v for _, v in
                   reg.series("raft_program_device_seconds_total"))

    dev0 = dev_total()
    usage0 = svc.session.usage.device_ns_total
    deck0 = len(svc.session.deck.snapshot())
    assert dev0 > 0  # the steady compute moved the counter: non-vacuous

    hit = svc.handle(request(la, ra, rid="hit"))
    assert hit["status"] == "ok"
    assert hit["quality"] == "cache:exact"
    assert hit["iters"] == cold["iters"]
    assert hit["disparity"].tobytes() == cold["disparity"].tobytes()
    assert dev_total() == dev0
    assert svc.session.usage.device_ns_total == usage0
    assert len(svc.session.deck.snapshot()) == deck0
    assert int(reg.value("raft_cache_hits_total")) == 1
    # the served hit array is a COPY: mutating it cannot poison the store
    hit["disparity"][0, 0] = 1e6
    hit2 = svc.handle(request(la, ra, rid="hit2"))
    assert hit2["disparity"].tobytes() == cold["disparity"].tobytes()
    # outcome accounting: hits count ok (+degraded under the
    # label-not-full convention), and the per-tenant usage rollup
    # carries the cache columns
    counts = {labels["outcome"]: int(v) for labels, v in
              reg.series("raft_requests_total")}
    assert counts["ok"] == 4
    assert counts["degraded"] == 2  # the two cache:exact labels
    doc = svc.session.usage.doc()
    assert doc["by_tenant"]["default"]["cache"]["hits"] == 2
    assert doc["by_tenant"]["default"]["cache"]["misses"] == 2


def test_fingerprint_change_invalidates(tiny_params, tiny_cfg):
    """The staleness contract: an effective breaker trip changes the
    session fingerprint, and every previously-deposited entry becomes
    structurally unreachable — the same bytes MISS and recompute."""
    svc = make_service(tiny_params, tiny_cfg)
    sess = svc.session
    la, ra = make_pair(0)
    svc.handle(request(la, ra, rid="cold"))
    assert svc.handle(request(la, ra))["quality"] == "cache:exact"
    fp_before = sess.fingerprint_id()
    # fused_encoders projects into an env switch -> the fingerprint
    # moves even though the tiny CPU program bytes may not.
    sess.breaker.trip("fused_encoders", "test")
    sess._rebuild("test trip")
    assert sess.fingerprint_id() != fp_before
    hits_before = int(svc.registry.value("raft_cache_hits_total"))
    r = svc.handle(request(la, ra, rid="after-trip"))
    assert r["quality"] == "full"  # recomputed, never served stale
    assert int(svc.registry.value("raft_cache_hits_total")) == hits_before


def test_tenant_isolation(tiny_params, tiny_cfg):
    """Tenant A's scene is never served to tenant B, even for
    bit-identical uploads — the tenant is part of the key, so the miss
    is structural, not probabilistic."""
    svc = make_service(tiny_params, tiny_cfg)
    la, ra = make_pair(0)
    ra1 = svc.handle(request(la, ra, tenant="alice"))
    assert svc.handle(request(la, ra, tenant="alice"))["quality"] == \
        "cache:exact"
    rb1 = svc.handle(request(la, ra, tenant="bob"))
    assert rb1["quality"] == "full"  # bob's first sight: computed
    # determinism means the bytes agree — but bob's came off the device
    assert rb1["disparity"].tobytes() == ra1["disparity"].tobytes()
    doc = svc.session.usage.doc()
    assert doc["by_tenant"]["alice"]["cache"]["hits"] == 1
    assert doc["by_tenant"]["bob"]["cache"]["hits"] == 0


def test_tenant_subcap_evicts_own_lru(tiny_params, tiny_cfg):
    """A tenant at its sub-cap evicts its OWN least-recently-used entry,
    never another tenant's (pinned: bob's entry survives alice's
    churn)."""
    svc = make_service(tiny_params, tiny_cfg)
    cache = svc.cache
    # Entry ~ disparity(9600) + flow + sig + overhead; sub-cap sized to
    # hold exactly one such entry per tenant.
    cache.per_tenant = 16_000
    a1, ra1 = make_pair(10)
    a2, ra2 = make_pair(11)
    b1, rb1 = make_pair(12)
    svc.handle(request(b1, rb1, tenant="bob"))
    svc.handle(request(a1, ra1, tenant="alice"))
    svc.handle(request(a2, ra2, tenant="alice"))  # evicts alice's first
    assert int(svc.registry.value("raft_cache_evictions_total")) == 1
    assert svc.handle(request(b1, rb1, tenant="bob"))["quality"] == \
        "cache:exact"       # bob untouched
    assert svc.handle(request(a2, ra2, tenant="alice"))["quality"] == \
        "cache:exact"       # alice's newest survived
    assert svc.handle(request(a1, ra1, tenant="alice"))["quality"] == \
        "full"              # alice's oldest was the victim
    assert int(svc.registry.value(
        "raft_tenant_cache_evictions_total", tenant="alice")) >= 1


def test_ttl_expiry_under_fakeclock(tiny_params, tiny_cfg):
    clock = FakeClock()
    svc = make_service(tiny_params, tiny_cfg, clock=clock,
                       cache_ttl_ms=5_000.0)
    la, ra = make_pair(0)
    svc.handle(request(la, ra))
    assert svc.handle(request(la, ra))["quality"] == "cache:exact"
    clock.sleep(60.0)  # way past the 5 s TTL
    r = svc.handle(request(la, ra))
    assert r["quality"] == "full"  # expired: recomputed
    assert int(svc.registry.value("raft_cache_expired_total")) >= 1
    assert svc.cache.status()["entries"] == 1  # the fresh re-deposit


def test_byte_cap_accounting_and_eviction_to_zero(tiny_params, tiny_cfg):
    """The byte budget is a hard bound throughout a deposit storm, the
    gauge tracks the accounted truth, and drop_all() zeroes it."""
    svc = make_service(tiny_params, tiny_cfg)
    cache = svc.cache
    cache.max_bytes = 40_000       # ~3 entries
    cache.per_tenant = 40_000
    for i in range(8):
        la, ra = make_pair(100 + i)
        svc.handle(request(la, ra, rid=i))
        assert cache.status()["bytes"] <= cache.max_bytes
        assert int(svc.registry.value("raft_cache_bytes")) == \
            cache.status()["bytes"]
    st = cache.status()
    assert st["evictions"] >= 5 and st["entries"] >= 1
    assert cache.drop_all() == st["entries"]
    st = cache.status()
    assert st["entries"] == 0 and st["bytes"] == 0
    assert int(svc.registry.value("raft_cache_bytes")) == 0
    assert int(svc.registry.value("raft_cache_entries")) == 0


def test_oversize_entry_refused(tiny_params, tiny_cfg):
    svc = make_service(tiny_params, tiny_cfg)
    svc.cache.max_bytes = 100  # smaller than any entry
    la, ra = make_pair(0)
    svc.handle(request(la, ra))
    st = svc.cache.status()
    assert st["entries"] == 0 and st["deposits_refused"] >= 1


# ---------------------------------------------------------------------------
# Near tier.
# ---------------------------------------------------------------------------


def test_near_tier_disabled_at_zero_tol(tiny_params, tiny_cfg):
    """tolerance=0 means fully disabled: no seed stamping, no near
    counters, the sequential path keeps its classic (non-segmented)
    route."""
    svc = make_service(tiny_params, tiny_cfg)  # near_tol defaults 0
    assert not svc.cache.wants_flow
    la, ra = make_pair(0)
    svc.handle(request(la, ra))
    req = request(perturb(la), ra)
    r = svc.handle(req)
    assert r["quality"] == "full"
    assert "_flow_init" not in req and "_cache_warm" not in req
    assert int(svc.registry.value("raft_cache_near_hits_total")) == 0


def test_near_tier_sequential_warm_label(tiny_params, tiny_cfg):
    """Sequential near hit: a perturbed duplicate is seeded from the
    stored neighbor's 1/8-res flow through prepare_warm, exits through
    the convergence monitor, and is labeled warm:cache:k with k == the
    iterations actually run.  Stream metrics stay untouched — the seed
    is the cache's, not a stream session's."""
    svc = make_service(tiny_params, tiny_cfg, cache_near_tol=8.0)
    assert svc.cache.wants_flow
    la, ra = make_pair(0)
    cold = svc.handle(request(la, ra))
    assert cold["quality"] == "full"
    assert svc.cache.status()["entries"] == 1
    req = request(perturb(la), ra, converge_tol=1e9)
    r = svc.handle(req)
    assert r["status"] == "ok"
    assert r["quality"].startswith("warm:cache:"), r["quality"]
    assert int(r["quality"].rsplit(":", 1)[1]) == r["iters"]
    assert r["iters"] < 4  # converged early — fewer than valid_iters
    assert req.get("_cache_warm") is True
    assert int(svc.registry.value("raft_cache_near_hits_total")) == 1
    assert int(svc.registry.value("raft_stream_warm_joins_total")) == 0
    assert int(svc.registry.value("raft_stream_converged_total")) == 0
    doc = svc.session.usage.doc()
    assert doc["by_tenant"]["default"]["cache"]["near_hits"] == 1
    # a warm-seeded response is never deposited as an exact entry
    assert svc.cache.status()["entries"] == 1


def test_near_tier_batched_warm_label(tiny_params, tiny_cfg):
    svc = make_service(tiny_params, tiny_cfg, max_batch=2,
                       cache_near_tol=8.0).start()
    try:
        la, ra = make_pair(0)
        assert svc.submit(request(la, ra)).result(
            timeout=300)["quality"] == "full"
        r = svc.submit(request(perturb(la), ra,
                               converge_tol=1e9)).result(timeout=300)
        assert r["quality"].startswith("warm:cache:")
        assert int(r["quality"].rsplit(":", 1)[1]) == r["iters"]
        assert int(svc.registry.value(
            "raft_stream_warm_joins_total")) == 0
        # deck tick rows carry the cumulative hit column
        ticks = [t for t in svc.session.deck.snapshot()
                 if t["kind"] == "tick"]
        assert ticks and all("cache_hits" in t for t in ticks)
        exact = svc.submit(request(la, ra)).result(timeout=300)
        assert exact["quality"] == "cache:exact"
    finally:
        svc.stop()


def test_near_tier_respects_tenant_and_tolerance(tiny_params, tiny_cfg):
    """A neighbor is only a neighbor within the SAME tenant and within
    the signature tolerance — a different tenant's scene or a genuinely
    different image never seeds."""
    svc = make_service(tiny_params, tiny_cfg, cache_near_tol=3.0)
    la, ra = make_pair(0)
    svc.handle(request(la, ra, tenant="alice"))
    # same bytes-ish, wrong tenant: cold
    req = request(perturb(la), ra, tenant="bob", converge_tol=1e9)
    assert "warm" not in svc.handle(req)["quality"]
    # right tenant, unrelated image (distance >> tol): cold
    lz, rz = make_pair(99)
    req = request(lz, rz, tenant="alice", converge_tol=1e9)
    r = svc.handle(req)
    assert not r["quality"].startswith("warm:cache:")
    # right tenant, tiny perturbation: warm
    req = request(perturb(la, sigma=1.0), ra, tenant="alice",
                  converge_tol=1e9)
    assert svc.handle(req)["quality"].startswith("warm:cache:")


def test_signature_math():
    rng = np.random.default_rng(0)
    img = rng.uniform(0, 255, (1, 64, 96, 3)).astype(np.float32)
    sig = block_signature(img)
    assert sig.shape == (16, 16) and sig.dtype == np.float32
    assert signature_distance(sig, sig) == 0.0
    shifted = block_signature(img + 5.0)
    assert signature_distance(sig, shifted) == pytest.approx(5.0, abs=0.1)
    other = block_signature(
        rng.uniform(0, 255, (1, 64, 96, 3)).astype(np.float32))
    assert signature_distance(sig, other) > 5.0
    assert signature_distance(sig, np.zeros((8, 8))) == float("inf")


# ---------------------------------------------------------------------------
# Churn storm: bounded bytes, flat /metrics (the hygiene regression).
# ---------------------------------------------------------------------------


def test_churn_storm_cannot_grow_bytes_or_metrics(tiny_params, tiny_cfg):
    """200 tenants x 500 deposits against a small budget: cache bytes
    never exceed the cap, and past the usage label bound the /metrics
    exposition is PROVABLY flat (the PR 10/12 label-hygiene mirror)."""
    svc = make_service(tiny_params, tiny_cfg)
    cache = svc.cache
    cache.max_bytes = 60_000
    cache.per_tenant = 60_000
    sess = svc.session
    sess.usage.max_tenants = 4  # force the __other__ overflow quickly
    la, ra = make_pair(0)
    # Drive admit/deposit directly (the storm is about the table, not
    # the device): each "request" is a distinct scene for a distinct
    # tenant, stamped through the real admission path.
    baseline_lines = None
    for i in range(500):
        tenant = f"churn-{i % 200}"
        lj = la + np.float32(i % 251)  # distinct bytes per deposit
        req = {"left": lj, "right": ra, "tenant": tenant}
        assert cache.admit(req) is None
        resp = {"status": "ok", "quality": "full",
                "disparity": np.zeros((H, W), np.float32), "iters": 4}
        cache.deposit(req, resp)
        assert cache.status()["bytes"] <= cache.max_bytes
        if i == 20:
            baseline_lines = len(
                svc.metrics_text().splitlines())
    assert baseline_lines is not None
    final_lines = len(svc.metrics_text().splitlines())
    assert final_lines == baseline_lines, (
        f"/metrics grew {baseline_lines} -> {final_lines} under tenant "
        f"churn — a label leak")
    st = cache.status()
    assert st["bytes"] <= cache.max_bytes
    assert st["evictions"] > 0


# ---------------------------------------------------------------------------
# Lifecycle: drain/stop drop, stream interplay.
# ---------------------------------------------------------------------------


def test_drain_drops_cache(tiny_params, tiny_cfg):
    svc = make_service(tiny_params, tiny_cfg, max_batch=2).start()
    la, ra = make_pair(0)
    assert svc.submit(request(la, ra)).result(timeout=300)["status"] == "ok"
    assert svc.cache.status()["entries"] == 1
    assert svc.drain() is True
    st = svc.cache.status()
    assert st["entries"] == 0 and st["bytes"] == 0
    assert int(svc.registry.value("raft_cache_bytes")) == 0


def test_deposit_refused_for_degraded_and_stale(tiny_params, tiny_cfg):
    """Only cold full-quality responses under the live fingerprint are
    stored — refusal is the bit-exactness guarantee."""
    svc = make_service(tiny_params, tiny_cfg)
    cache = svc.cache
    la, ra = make_pair(0)
    req = {"left": la, "right": ra}
    assert cache.admit(req) is None
    # degraded quality: refused
    cache.deposit(req, {"status": "ok", "quality": "reduced_iters:2",
                        "disparity": np.zeros((H, W), np.float32),
                        "iters": 2})
    assert cache.status()["entries"] == 0
    # warm-seeded: refused
    req2 = {"left": la, "right": ra}
    assert cache.admit(req2) is None
    req2["_flow_init"] = np.zeros((1, 8, 8, 1), np.float32)
    cache.deposit(req2, {"status": "ok", "quality": "full",
                         "disparity": np.zeros((H, W), np.float32),
                         "iters": 4})
    assert cache.status()["entries"] == 0
    # fingerprint-stale: refused
    req3 = {"left": la, "right": ra}
    assert cache.admit(req3) is None
    svc.session.breaker.trip("fused_encoders", "test")
    svc.session._rebuild("test")
    cache.deposit(req3, {"status": "ok", "quality": "full",
                         "disparity": np.zeros((H, W), np.float32),
                         "iters": 4})
    assert cache.status()["entries"] == 0
    assert cache.status()["deposits_refused"] == 3


def test_exact_hit_keeps_stream_session_warm(tiny_params, tiny_cfg):
    """A stream member hitting the exact tier still deposits the
    entry's held flow into its stream session — the stream does not go
    cold just because the answer came for free."""
    svc = make_service(tiny_params, tiny_cfg, max_batch=2,
                       cache_near_tol=8.0).start()
    try:
        la, ra = make_pair(0)
        r1 = svc.submit(request(la, ra, tenant="cam",
                                stream="s1")).result(timeout=300)
        assert r1["status"] == "ok"
        # identical frame 2: exact hit, but the session must stay warm
        r2 = svc.submit(request(la, ra, tenant="cam",
                               stream="s1")).result(timeout=300)
        assert r2["quality"] == "cache:exact"
        # perturbed frame 3 on the same stream: the SESSION seed wins
        # (stream warm join), proving the hit's deposit kept it warm
        req3 = request(perturb(la), ra, tenant="cam", stream="s1",
                       converge_tol=1e9)
        r3 = svc.submit(req3).result(timeout=300)
        assert r3["status"] == "ok"
        assert r3["quality"].startswith("converged:"), r3["quality"]
        assert int(svc.registry.value(
            "raft_stream_warm_joins_total")) == 1
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Disk spill (RAFT_CACHE_DIR).
# ---------------------------------------------------------------------------


def test_disk_spill_roundtrip(tiny_params, tiny_cfg, tmp_path):
    """An entry evicted from RAM spills to RAFT_CACHE_DIR and a later
    exact match promotes it back — served cache:exact, bit-identical."""
    svc = make_service(tiny_params, tiny_cfg,
                       cache_dir=str(tmp_path / "spill"))
    cache = svc.cache
    cache.max_bytes = 16_000   # one entry at a time
    cache.per_tenant = 16_000
    la, ra = make_pair(0)
    lb, rb = make_pair(1)
    cold_a = svc.handle(request(la, ra))
    svc.handle(request(lb, rb))   # evicts A -> spilled to disk
    assert int(svc.registry.value("raft_cache_spills_total")) == 1
    assert cache.status()["disk"]["bytes"] > 0
    r = svc.handle(request(la, ra))
    assert r["quality"] == "cache:exact"
    assert r["disparity"].tobytes() == cold_a["disparity"].tobytes()
    assert int(svc.registry.value("raft_cache_disk_hits_total")) == 1


def test_disk_spill_ttl_and_budget(tiny_params, tiny_cfg, tmp_path):
    clock = FakeClock()
    svc = make_service(tiny_params, tiny_cfg, clock=clock,
                       cache_dir=str(tmp_path / "spill"),
                       cache_ttl_ms=5_000.0)
    cache = svc.cache
    cache.max_bytes = 16_000
    cache.per_tenant = 16_000
    la, ra = make_pair(0)
    lb, rb = make_pair(1)
    svc.handle(request(la, ra))
    svc.handle(request(lb, rb))   # A spilled
    clock.sleep(60.0)             # past the TTL on the session clock
    r = svc.handle(request(la, ra))
    assert r["quality"] == "full"  # expired spill is a miss + unlink
    spill_dir = tmp_path / "spill"
    # budget prune: disk bytes stay bounded by max_bytes
    for i in range(6):
        li, ri = make_pair(50 + i)
        svc.handle(request(li, ri))
    disk_bytes = sum(f.stat().st_size for f in spill_dir.glob("*.npz"))
    assert disk_bytes <= cache.max_bytes


def test_submit_not_running_beats_cache(tiny_params, tiny_cfg):
    """submit()'s lifecycle contract survives the cache: a stopped (or
    never-started) service rejects not_running even for bytes it could
    answer from the store — a service must not keep serving from the
    grave (review finding, pinned)."""
    svc = make_service(tiny_params, tiny_cfg, max_batch=2).start()
    la, ra = make_pair(0)
    assert svc.submit(request(la, ra)).result(timeout=300)["status"] == "ok"
    svc.stop()
    # Simulate a still-warm store on a stopped service (drop_all cleared
    # RAM; a RAFT_CACHE_DIR spill would survive exactly like this).
    req = {"left": la.copy(), "right": ra.copy()}
    assert svc.cache.admit(req) is None
    svc.cache.deposit(req, {"status": "ok", "quality": "full",
                            "disparity": np.zeros((H, W), np.float32),
                            "iters": 4})
    assert svc.cache.status()["entries"] == 1
    r = svc.submit(request(la, ra)).result(timeout=10)
    assert r["status"] == "rejected" and r["code"] == "not_running", r


def test_disk_promotion_respects_shrunk_budget(tiny_params, tiny_cfg,
                                               tmp_path):
    """A spill written under a larger budget than the current one is
    served once but never promoted — raft_cache_bytes can never exceed
    RAFT_CACHE_BYTES, restart-with-smaller-budget included (review
    finding, pinned)."""
    spill = str(tmp_path / "spill")
    svc = make_service(tiny_params, tiny_cfg, cache_dir=spill)
    svc.cache.max_bytes = 16_000
    svc.cache.per_tenant = 16_000
    la, ra = make_pair(0)
    lb, rb = make_pair(1)
    cold = svc.handle(request(la, ra))
    svc.handle(request(lb, rb))   # A evicted -> spilled
    # "Restart" with a budget smaller than one entry.
    svc2 = make_service(tiny_params, tiny_cfg, cache_dir=spill)
    svc2.cache.max_bytes = 1_000
    svc2.cache.per_tenant = 1_000
    r = svc2.handle(request(la, ra))
    assert r["quality"] == "cache:exact"  # the spill still serves once
    assert r["disparity"].tobytes() == cold["disparity"].tobytes()
    st = svc2.cache.status()
    assert st["entries"] == 0 and st["bytes"] == 0  # never promoted


def test_corrupt_spill_is_a_miss(tiny_params, tiny_cfg, tmp_path):
    svc = make_service(tiny_params, tiny_cfg,
                       cache_dir=str(tmp_path / "spill"))
    cache = svc.cache
    cache.max_bytes = 16_000
    cache.per_tenant = 16_000
    la, ra = make_pair(0)
    lb, rb = make_pair(1)
    svc.handle(request(la, ra))
    svc.handle(request(lb, rb))
    for f in (tmp_path / "spill").glob("*.npz"):
        f.write_bytes(b"garbage")
    r = svc.handle(request(la, ra))
    assert r["status"] == "ok" and r["quality"] == "full"


# ---------------------------------------------------------------------------
# The /healthz block and wire-facing surface.
# ---------------------------------------------------------------------------


def test_status_block_and_healthz(tiny_params, tiny_cfg):
    svc = make_service(tiny_params, tiny_cfg)
    la, ra = make_pair(0)
    svc.handle(request(la, ra))
    svc.handle(request(la, ra))
    doc = svc.status()
    cb = doc["cache"]
    assert cb["enabled"] and cb["hits"] == 1 and cb["misses"] == 1
    assert cb["hit_ratio"] == pytest.approx(0.5)
    assert cb["entries"] == 1 and cb["bytes"] > 0
    # the block is JSON-serializable (the /healthz contract)
    import json
    json.dumps(doc, default=str)


def test_gl002_sensitivity_env_reads_are_literal():
    """The four RAFT_CACHE_* reads in serve/cache.py must be literal
    os.environ reads (GL002's registry cross-check depends on seeing
    them); this guards the file-level convention the analysis test pins
    tree-wide."""
    import inspect

    from raft_stereo_tpu.serve import cache as cache_mod
    src = inspect.getsource(cache_mod)
    for knob in ("RAFT_CACHE_BYTES", "RAFT_CACHE_TTL_MS",
                 "RAFT_CACHE_NEAR_TOL", "RAFT_CACHE_DIR"):
        assert f'os.environ.get("{knob}"' in src, knob


# ---------------------------------------------------------------------------
# Concurrent-writer safety (graftfleet r20): two instances sharing one
# RAFT_CACHE_DIR must never publish a torn entry.
# ---------------------------------------------------------------------------


def test_spill_tmp_names_unique_per_writer(tiny_params, tiny_cfg,
                                           tmp_path, monkeypatch):
    """The atomic tmp+rename path must use a UNIQUE tmp name per writer:
    with the old fixed "<path>.tmp" suffix, two caches spilling the same
    key concurrently would open the SAME tmp file — writer B's open()
    truncates the bytes writer A is mid-np.savez on, and A's os.replace
    then publishes B's torn prefix under the final name.  Also pinned:
    tmp names never end in ".npz", so the disk accounting scans and the
    prune can never count or load an in-progress write."""
    import os as os_mod

    from raft_stereo_tpu.serve.cache import CacheEntry

    spill = str(tmp_path / "spill")
    svc = make_service(tiny_params, tiny_cfg, cache_dir=spill)
    c1 = svc.cache
    c2 = ResponseCache(svc.session, max_bytes=64 << 20, cache_dir=spill)

    recorded = []
    real_replace = os_mod.replace

    def spy(src, dst, *a, **kw):
        recorded.append((src, dst))
        return real_replace(src, dst, *a, **kw)

    monkeypatch.setattr("os.replace", spy)

    key = ("exact", "contested", 1)
    sig = np.zeros(64, np.float32)

    def entry(cache, fill):
        return CacheEntry(key, "default", "default", sig,
                          np.full((H, W), fill, np.float32), None,
                          None, 4, 0.0)

    c1._spill(entry(c1, 1.0))
    c2._spill(entry(c2, 2.0))
    spill_writes = [(s, d) for s, d in recorded
                    if d.startswith(spill)]
    assert len(spill_writes) == 2
    (src1, dst1), (src2, dst2) = spill_writes
    assert dst1 == dst2, "same key must target the same final path"
    assert src1 != src2, (
        "two writers shared one tmp path — the torn-entry race")
    for src in (src1, src2):
        assert not src.endswith(".npz"), (
            "a tmp name ending in .npz is visible to the disk scans")
    leftovers = [f for f in os_mod.listdir(spill) if ".tmp" in f]
    assert leftovers == [], leftovers


def test_two_caches_racing_deposits_never_serve_torn(tiny_params,
                                                     tiny_cfg,
                                                     tmp_path):
    """Two ResponseCache objects hammer the SAME key's spill path from
    concurrent threads; whatever write wins, the published file must
    always load as a COMPLETE entry (one writer's payload, never an
    interleaving) and the promote path must serve it."""
    import threading as threading_mod

    from raft_stereo_tpu.serve.cache import CacheEntry

    spill = str(tmp_path / "spill")
    svc = make_service(tiny_params, tiny_cfg, cache_dir=spill)
    caches = [svc.cache,
              ResponseCache(svc.session, max_bytes=64 << 20,
                            cache_dir=spill)]
    key = ("exact", "contested", 2)
    sig = np.zeros(64, np.float32)
    fills = {0: 10.0, 1: 20.0}
    errors = []

    def writer(idx):
        cache = caches[idx]
        try:
            for _ in range(25):
                cache._spill(CacheEntry(
                    key, "default", "default", sig,
                    np.full((H, W), fills[idx], np.float32), None,
                    None, 4, 0.0))
        except Exception as e:  # noqa: BLE001 — fail the test with it
            errors.append(e)

    threads = [threading_mod.Thread(target=writer, args=(i,))
               for i in (0, 1) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors

    # The published file is ONE complete payload — loadable, correct
    # key, disparity uniformly one writer's fill value.
    path = caches[0]._path_for(key)
    with np.load(path) as z:
        import json as json_mod
        meta = json_mod.loads(bytes(z["meta"]).decode())
        assert meta["key"] == repr(key)
        disp = np.array(z["disparity"])
    assert disp.shape == (H, W)
    assert disp.min() == disp.max() and disp.min() in fills.values(), (
        "torn spill: interleaved bytes from two writers")
    # and the promote path serves it
    entry = caches[1]._disk_lookup(key, "default", "default", now=1.0)
    assert entry is not None and entry.iters == 4
    assert [f for f in (tmp_path / "spill").iterdir()
            if ".tmp" in f.name] == []

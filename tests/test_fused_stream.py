"""Streaming Pallas scan-body kernels (ops/pallas_stream.py) vs XLA oracles.

Interpret mode on CPU. Two kinds of evidence:
- integer-valued inputs are EXACT in fp32, so any tap/shift/lag/boundary-mask
  bug shows as an integer-sized error while legal reassociation shows as 0;
- float inputs bound the rounding-amplification envelope.

Plus the end-to-end bf16 test-mode forward (the only path that engages the
head-chained fused_gru_head kernel) against the same forward with
``fused_update=False`` (pure XLA).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_stereo_tpu.config import RAFTStereoConfig

# The whole module is a kernel oracle battery: on a TPU host,
# RAFT_TEST_ONCHIP=1 (scripts/run_onchip_battery.sh) runs every test
# COMPILED through Mosaic instead of interpret-mode on CPU.
pytestmark = pytest.mark.kernel_battery
from raft_stereo_tpu.models import init_raft_stereo, raft_stereo_forward
from raft_stereo_tpu.models.update import (
    apply_conv_gru, apply_flow_head, apply_motion_encoder, init_conv_gru,
    init_flow_head, init_motion_encoder)
from raft_stereo_tpu.ops.pallas_stream import (
    fused_conv_gru_fwd_impl, fused_motion_fwd_impl, prepare_gru_context)


def _gru_case(key, h_, w_, ch, parts_c, dtype):
    cin = sum(parts_c)
    p = init_conv_gru(key, ch, cin)
    hp = init_flow_head(jax.random.PRNGKey(9), ch, 64, 2)
    ks = jax.random.split(key, 8)
    h = jax.random.normal(ks[0], (1, h_, w_, ch), dtype) * 0.5
    xs = [jax.random.normal(k, (1, h_, w_, c), dtype)
          for k, c in zip(ks[1:1 + len(parts_c)], parts_c)]
    ctx = tuple(jax.random.normal(k, (1, h_, w_, ch), dtype) * 0.3
                for k in ks[5:8])
    return p, hp, h, xs, ctx


@pytest.mark.parametrize("h_,w_,ch,parts_c,dtype,tol", [
    (16, 24, 128, (128, 128), jnp.float32, 1e-4),
    (8, 13, 64, (64,), jnp.float32, 1e-4),
    (24, 9, 32, (32, 32), jnp.float32, 1e-4),
    (16, 24, 128, (128, 128), jnp.bfloat16, 5e-2),
])
def test_fused_gru_matches_oracle(h_, w_, ch, parts_c, dtype, tol):
    p, hp, h, xs, ctx = _gru_case(jax.random.PRNGKey(0), h_, w_, ch,
                                  parts_c, dtype)
    czrq = prepare_gru_context(p, ctx, dtype)
    ref = apply_conv_gru(p, h, ctx, *xs)
    got, _ = fused_conv_gru_fwd_impl(p, h, czrq, *xs)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < tol, err
    # Head-chained variant: h' must be identical; the delta-x matches the
    # FlowHead applied to the kernel's own h' (isolating the head from GRU
    # rounding amplification). The kernel omits conv2.b[0] (callers add it).
    got2, dx = fused_conv_gru_fwd_impl(p, h, czrq, *xs, head_p=hp)
    assert float(jnp.max(jnp.abs(
        got2.astype(jnp.float32) - got.astype(jnp.float32)))) == 0.0
    dref = apply_flow_head(hp, got2)[..., :1] - hp["conv2"]["b"][0]
    derr = float(jnp.max(jnp.abs(dx - dref.astype(jnp.float32))))
    assert derr < 3 * tol, derr


def test_fused_gru_and_motion_batched_match_per_sample():
    """B>1 rides as the outer Pallas grid dim (r4): every sample's row
    stream must restart cleanly — asserted by BIT-equality between the
    batched run and per-sample B=1 runs (a ring that leaks rows across
    the sample boundary shows up immediately), plus the oracle check."""
    key = jax.random.PRNGKey(0)
    B, h_, w_, ch = 3, 16, 24, 64
    p = init_conv_gru(key, ch, 2 * ch)
    ks = jax.random.split(key, 8)
    h = jax.random.normal(ks[0], (B, h_, w_, ch)) * 0.5
    xs = [jax.random.normal(k, (B, h_, w_, ch)) for k in ks[1:3]]
    ctx = tuple(jax.random.normal(k, (B, h_, w_, ch)) * 0.3
                for k in ks[3:6])
    czrq = prepare_gru_context(p, ctx, jnp.float32)
    ref = apply_conv_gru(p, h, ctx, *xs)
    got, _ = fused_conv_gru_fwd_impl(p, h, czrq, *xs)
    assert float(jnp.abs(got - ref).max()) < 1e-4
    for b in range(B):
        g1, _ = fused_conv_gru_fwd_impl(p, h[b:b + 1], czrq[b:b + 1],
                                        *[x[b:b + 1] for x in xs])
        assert float(jnp.abs(got[b:b + 1] - g1).max()) == 0.0

    cfg = RAFTStereoConfig()
    pm = init_motion_encoder(key, cfg)
    corr = jax.random.normal(key, (B, h_, w_, cfg.cor_planes))
    flow = jax.random.normal(key, (B, h_, w_, 2)).at[..., 1].set(0.0)
    refm = apply_motion_encoder(pm, flow, corr)
    gotm = fused_motion_fwd_impl(pm, flow, corr)
    assert float(jnp.abs(gotm - refm).max()) < 1e-3
    for b in range(B):
        g1 = fused_motion_fwd_impl(pm, flow[b:b + 1], corr[b:b + 1])
        assert float(jnp.abs(gotm[b:b + 1] - g1).max()) == 0.0


def _gru1632_case(key, h16_, w16_, ch, dtype, b=1):
    from raft_stereo_tpu.models.update import init_conv_gru
    h32_, w32_ = h16_ // 2, w16_ // 2
    kp = jax.random.split(key, 12)
    p16 = init_conv_gru(kp[0], ch, 2 * ch)   # x parts: pool(net0) + up
    p32 = init_conv_gru(kp[1], ch, ch)       # x part: pool(net1)
    h16 = jax.random.normal(kp[2], (b, h16_, w16_, ch), dtype) * 0.5
    h32 = jax.random.normal(kp[3], (b, h32_, w32_, ch), dtype) * 0.5
    ctx16 = tuple(jax.random.normal(k, (b, h16_, w16_, ch), dtype) * 0.3
                  for k in kp[4:7])
    ctx32 = tuple(jax.random.normal(k, (b, h32_, w32_, ch), dtype) * 0.3
                  for k in kp[7:10])
    x0p = jax.random.normal(kp[10], (b, h16_, w16_, ch), dtype)
    x1p = jax.random.normal(kp[11], (b, h32_, w32_, ch), dtype)
    return p16, p32, h16, h32, ctx16, ctx32, x0p, x1p


@pytest.mark.parametrize("h16_,w16_,ch,dtype,tol", [
    (16, 24, 128, jnp.float32, 1e-4),
    (32, 18, 64, jnp.float32, 1e-4),
    (48, 16, 32, jnp.float32, 1e-4),
    (16, 24, 128, jnp.bfloat16, 5e-2),
])
def test_fused_gru1632_matches_oracle(h16_, w16_, ch, dtype, tol):
    """Co-scheduled gru16+gru32 kernel vs the serial XLA composition
    (gru32 -> aligned-corners upsample -> gru16)."""
    from raft_stereo_tpu.ops.pallas_stream import (
        _gru1632_oracle, fused_gru1632_fwd_impl, gru1632_th)
    assert gru1632_th(h16_, w16_) > 0
    p16, p32, h16, h32, ctx16, ctx32, x0p, x1p = _gru1632_case(
        jax.random.PRNGKey(0), h16_, w16_, ch, dtype)
    czrq16 = prepare_gru_context(p16, ctx16, dtype)
    czrq32 = prepare_gru_context(p32, ctx32, dtype)
    ref16, ref32 = _gru1632_oracle(p16, p32, h16, h32, ctx16, ctx32,
                                   x0p, x1p)
    got16, got32 = fused_gru1632_fwd_impl(p16, p32, h16, h32, czrq16,
                                          czrq32, x0p, x1p)
    for got, ref in ((got32, ref32), (got16, ref16)):
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        assert err < tol, err


def test_fused_gru1632_bitwise_matches_serial_kernels():
    """The co-scheduled kernel must be BIT-IDENTICAL to the serial fused
    path it replaces (fused_conv_gru x2 + XLA interp_align_corners in
    bf16): the in-kernel upsample reuses resize.py's banded-matrix
    weights and rounds to bf16 between the H and W passes exactly where
    the XLA einsum pair does, so any mismatch is a real scheduling or
    windowing bug — not tolerance."""
    from raft_stereo_tpu.ops.pallas_stream import (
        fused_conv_gru_fwd_impl, fused_gru1632_fwd_impl)
    from raft_stereo_tpu.ops.resize import interp_align_corners
    dtype = jnp.bfloat16
    p16, p32, h16, h32, ctx16, ctx32, x0p, x1p = _gru1632_case(
        jax.random.PRNGKey(1), 32, 24, 128, dtype)
    czrq16 = prepare_gru_context(p16, ctx16, dtype)
    czrq32 = prepare_gru_context(p32, ctx32, dtype)
    ser32, _ = fused_conv_gru_fwd_impl(p32, h32, czrq32, x1p)
    up = interp_align_corners(ser32, h16.shape[1:3])
    ser16, _ = fused_conv_gru_fwd_impl(p16, h16, czrq16, x0p, up)
    got16, got32 = fused_gru1632_fwd_impl(p16, p32, h16, h32, czrq16,
                                          czrq32, x0p, x1p)
    assert (np.asarray(got32, np.float32)
            == np.asarray(ser32, np.float32)).all()
    assert (np.asarray(got16, np.float32)
            == np.asarray(ser16, np.float32)).all()


def test_fused_gru1632_integer_exact():
    """Integer inputs are exact in fp32: any lag/window/boundary bug in
    the co-schedule shows as an integer-sized error."""
    from raft_stereo_tpu.models.update import init_conv_gru
    from raft_stereo_tpu.ops.pallas_stream import (
        _gru1632_oracle, fused_gru1632_fwd_impl)
    rng = np.random.default_rng(0)
    ch, h16_, w16_ = 32, 16, 24

    def ints(shape):
        return jnp.asarray(rng.integers(-2, 3, shape), jnp.float32)

    p16 = jax.tree.map(lambda t: ints(t.shape),
                       init_conv_gru(jax.random.PRNGKey(0), ch, 2 * ch))
    p32 = jax.tree.map(lambda t: ints(t.shape),
                       init_conv_gru(jax.random.PRNGKey(1), ch, ch))
    h16 = ints((1, h16_, w16_, ch))
    h32 = ints((1, h16_ // 2, w16_ // 2, ch))
    ctx16 = tuple(ints((1, h16_, w16_, ch)) for _ in range(3))
    ctx32 = tuple(ints((1, h16_ // 2, w16_ // 2, ch)) for _ in range(3))
    x0p = ints((1, h16_, w16_, ch))
    x1p = ints((1, h16_ // 2, w16_ // 2, ch))
    czrq16 = prepare_gru_context(p16, ctx16, jnp.float32)
    czrq32 = prepare_gru_context(p32, ctx32, jnp.float32)
    ref16, ref32 = _gru1632_oracle(p16, p32, h16, h32, ctx16, ctx32,
                                   x0p, x1p)
    got16, got32 = fused_gru1632_fwd_impl(p16, p32, h16, h32, czrq16,
                                          czrq32, x0p, x1p)
    # Unlike the relu-chain motion encoder, the GRU runs integer preacts
    # through sigmoid/tanh, so fp32 conv reassociation (XLA's one-pass
    # conv vs the ring's 9 dots) survives as ~1e-5 noise — same envelope
    # as test_fused_gru_matches_oracle. A mis-schedule would be O(1) and
    # LOCALIZED; the bitwise-vs-serial-kernels test pins exactness.
    d32 = np.asarray(jnp.abs(got32 - ref32))
    d16 = np.asarray(jnp.abs(got16 - ref16))
    assert d32.max() < 1e-4, d32.max()
    assert d16.max() < 1e-4, d16.max()
    assert d16[0].max(axis=(1, 2)).std() < d16.max()  # no row stands out


def test_fused_gru1632_batched_matches_per_sample():
    """B > 1 rides the outer grid dim: batched run must BIT-match
    per-sample runs (a window/ring leaking across samples shows here)."""
    from raft_stereo_tpu.ops.pallas_stream import fused_gru1632_fwd_impl
    p16, p32, h16, h32, ctx16, ctx32, x0p, x1p = _gru1632_case(
        jax.random.PRNGKey(2), 16, 16, 64, jnp.float32, b=3)
    czrq16 = prepare_gru_context(p16, ctx16, jnp.float32)
    czrq32 = prepare_gru_context(p32, ctx32, jnp.float32)
    got16, got32 = fused_gru1632_fwd_impl(p16, p32, h16, h32, czrq16,
                                          czrq32, x0p, x1p)
    for b in range(3):
        g16, g32 = fused_gru1632_fwd_impl(
            p16, p32, h16[b:b + 1], h32[b:b + 1], czrq16[b:b + 1],
            czrq32[b:b + 1], x0p[b:b + 1], x1p[b:b + 1])
        assert float(jnp.abs(got16[b:b + 1] - g16).max()) == 0.0
        assert float(jnp.abs(got32[b:b + 1] - g32).max()) == 0.0


def test_fused_gru1632_grads_match_oracle():
    """custom_vjp backward == grads of the XLA composition."""
    from raft_stereo_tpu.ops.pallas_stream import (
        _gru1632_oracle, fused_gru1632)
    import raft_stereo_tpu.ops.pallas_stream as ps
    p16, p32, h16, h32, ctx16, ctx32, x0p, x1p = _gru1632_case(
        jax.random.PRNGKey(3), 16, 16, 64, jnp.float32)
    czrq16 = prepare_gru_context(p16, ctx16, jnp.float32)
    czrq32 = prepare_gru_context(p32, ctx32, jnp.float32)
    old = ps.FORCE_FUSABLE_DTYPE
    ps.FORCE_FUSABLE_DTYPE = True
    try:
        def loss_fused(h16_, h32_, p16_, p32_):
            a, b = fused_gru1632(p16_, p32_, h16_, h32_, czrq16, czrq32,
                                 ctx16, ctx32, x0p, x1p)
            return (jnp.sum(a.astype(jnp.float32) ** 2)
                    + jnp.sum(b.astype(jnp.float32) ** 2))

        def loss_ref(h16_, h32_, p16_, p32_):
            a, b = _gru1632_oracle(p16_, p32_, h16_, h32_, ctx16, ctx32,
                                   x0p, x1p)
            return (jnp.sum(a.astype(jnp.float32) ** 2)
                    + jnp.sum(b.astype(jnp.float32) ** 2))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(h16, h32, p16, p32)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(h16, h32, p16, p32)
    finally:
        ps.FORCE_FUSABLE_DTYPE = old
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gr)):
        d = np.abs(np.asarray(a) - np.asarray(b)).max()
        s = np.abs(np.asarray(b)).max() + 1e-8
        assert d / s < 5e-3, (d, s)


def test_fused_gru1632_end_to_end_matches_serial(rng, monkeypatch):
    """Full bf16 test-mode forward with the co-scheduled gru16+gru32
    engaged vs the same forward forced onto the serial two-kernel path
    (RAFT_FUSE_GRU1632=0): bit-identical disparities, by construction.
    128x128 input -> 16x16 / 8x8 coarse scales, the smallest shapes the
    co-schedule supports."""
    from raft_stereo_tpu.ops.pallas_stream import gru1632_is_fusable
    cfg = RAFTStereoConfig(mixed_precision=True)
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1 = jnp.asarray(rng.uniform(0, 255, size=(1, 128, 128, 3)),
                       dtype=jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, size=(1, 128, 128, 3)),
                       dtype=jnp.float32)
    h16 = jnp.zeros((1, 16, 16, 128), jnp.bfloat16)
    h32 = jnp.zeros((1, 8, 8, 128), jnp.bfloat16)
    assert gru1632_is_fusable(h16, h32)  # the site engages at this size
    lr_f, up_f = raft_stereo_forward(params, cfg, img1, img2, iters=1,
                                     test_mode=True)
    monkeypatch.setenv("RAFT_FUSE_GRU1632", "0")
    lr_s, up_s = raft_stereo_forward(params, cfg, img1, img2, iters=1,
                                     test_mode=True)
    assert (np.asarray(up_f, np.float32) == np.asarray(up_s,
                                                       np.float32)).all()
    assert (np.asarray(lr_f, np.float32) == np.asarray(lr_s,
                                                       np.float32)).all()


def test_fused_motion_integer_exact():
    cfg = RAFTStereoConfig()
    rng = np.random.default_rng(0)
    pm = init_motion_encoder(jax.random.PRNGKey(0), cfg)
    pm = jax.tree.map(
        lambda t: jnp.asarray(rng.integers(-2, 3, t.shape), jnp.float32), pm)
    corr = jnp.asarray(rng.integers(-3, 4, (1, 16, 24, cfg.cor_planes)),
                       jnp.float32)
    # Model invariant: flow-y is identically zero (epipolar projection,
    # raft_stereo.py:120); the fused motion encoder relies on it (flow-x-
    # only f1 patches), so the oracle comparison feeds zero-y flow too.
    flow = jnp.asarray(rng.integers(-3, 4, (1, 16, 24, 2)), jnp.float32)
    flow = flow.at[..., 1].set(0.0)
    ref = apply_motion_encoder(pm, flow, corr)
    got = fused_motion_fwd_impl(pm, flow, corr)
    assert float(jnp.max(jnp.abs(got - ref))) == 0.0


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 5e-2),
                                       (jnp.bfloat16, 5e-2)])
def test_fused_motion_matches_oracle(dtype, tol):
    cfg = RAFTStereoConfig()
    key = jax.random.PRNGKey(0)
    pm = init_motion_encoder(key, cfg)
    corr = jax.random.normal(key, (1, 16, 24, cfg.cor_planes), dtype)
    flow = jax.random.normal(key, (1, 16, 24, 2), dtype)
    flow = flow.at[..., 1].set(0.0)  # model invariant (see integer test)
    ref = apply_motion_encoder(pm, flow, corr)
    got = fused_motion_fwd_impl(pm, flow, corr)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    assert err < tol, err


def test_fp32_test_mode_fused_vs_xla(rng, monkeypatch):
    """End-to-end check of the full fused scan body (cnet stem kernel,
    motion kernel, head-chained GRU kernel — the update=True /
    compute_mask=False branch only the fused path takes) against the pure
    XLA path, in fp32 where the comparison is tight. The FORCE hook lets
    fp32 through the bf16-only fusable gates; interpret mode has no VMEM
    ceiling, so this is test-only."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    monkeypatch.setattr(ps, "FORCE_FUSABLE_DTYPE", True)
    cfg_f = RAFTStereoConfig()
    cfg_x = RAFTStereoConfig(fused_update=False)
    params = init_raft_stereo(jax.random.key(0), cfg_f)
    img1 = jnp.asarray(rng.uniform(0, 255, size=(1, 32, 64, 3)),
                       dtype=jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, size=(1, 32, 64, 3)),
                       dtype=jnp.float32)
    lr_f, up_f = raft_stereo_forward(params, cfg_f, img1, img2, iters=3,
                                     test_mode=True)
    lr_x, up_x = raft_stereo_forward(params, cfg_x, img1, img2, iters=3,
                                     test_mode=True)
    # fp32 reassociation only, amplified by 3 recurrent iterations.
    np.testing.assert_allclose(np.asarray(lr_f), np.asarray(lr_x), atol=2e-2)
    np.testing.assert_allclose(np.asarray(up_f), np.asarray(up_x), atol=2e-2)


def test_bf16_test_mode_fused_runs(rng):
    """bf16 wiring smoke: the real (non-forced) fused path stays finite.
    Numerical agreement at bf16 on trained weights is pinned on-chip by
    scratch/cli_impl_consistency.py (EPE delta ~3e-3 px at 32 iters)."""
    cfg = RAFTStereoConfig(mixed_precision=True)
    params = init_raft_stereo(jax.random.key(0), cfg)
    img1 = jnp.asarray(rng.uniform(0, 255, size=(1, 32, 64, 3)),
                       dtype=jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, size=(1, 32, 64, 3)),
                       dtype=jnp.float32)
    lr3, up3 = raft_stereo_forward(params, cfg, img1, img2, iters=3,
                                   test_mode=True)
    assert np.isfinite(np.asarray(up3, dtype=np.float32)).all()


@pytest.mark.parametrize("hw", [(48, 24), (16, 800)])
def test_fused_cnet_stem_layer1_matches_oracle(hw):
    """Streaming frozen-BN stem+layer1 (ops/pallas_encoder.py) vs XLA.

    (16, 800) exercises the MULTI-strip path (nwb=2): the 8-aligned
    dynamic strip placement, the strip-delayed conv with its cross-strip
    halo columns, the per-strip delay rings and the trash-block output
    index maps — none of which the single-strip width 24 touches."""
    from raft_stereo_tpu.models.extractor import init_multi_basic_encoder
    from raft_stereo_tpu.ops.pallas_encoder import (
        fused_stem_layer1_impl, _oracle, _strip_wb)
    h_, w_ = hw
    assert (_strip_wb(w_) < w_) == (w_ == 800)  # (16,800) is multi-strip
    key = jax.random.PRNGKey(0)
    p = init_multi_basic_encoder(key, output_dim=[[128] * 3, [128] * 3],
                                 norm_fn="batch", downsample=2)
    x = jax.random.normal(key, (1, h_, w_, 3))
    ref = np.asarray(_oracle(p, x))
    got = np.asarray(fused_stem_layer1_impl(p, x))
    d = np.abs(got - ref)
    # fp32 reassociation through 5 convs (BN folded into weights vs applied
    # after); diffuse across rows — boundary bugs would localize.
    assert d.max() < 5e-2, d.max()
    assert d[0].max(axis=(1, 2)).std() < d.max()  # no row stands out


@pytest.mark.parametrize("hw", [(48, 24), (16, 800)])
def test_fused_fnet_stem_layer1_matches_oracle(hw):
    """Streamed one-pass-per-conv instance-norm stem+layer1 vs XLA."""
    from raft_stereo_tpu.models.extractor import init_basic_encoder
    from raft_stereo_tpu.ops.pallas_encoder import (
        fused_in_stem_layer1_impl, _in_oracle)
    h_, w_ = hw
    key = jax.random.PRNGKey(0)
    p = init_basic_encoder(key, output_dim=256, norm_fn="instance",
                           downsample=2)
    x = jax.random.normal(key, (1, h_, w_, 3))
    ref = np.asarray(_in_oracle(p, x))
    got = np.asarray(fused_in_stem_layer1_impl(p, x))
    assert np.abs(got - ref).max() < 5e-2, np.abs(got - ref).max()


@pytest.mark.parametrize("norm_fn", ["batch", "instance"])
def test_packed_entry_block_matches_unpacked(norm_fn):
    """Stride-2 residual block over the parity-packed trunk exit vs the same
    block over the unpacked (1, H, W, 64) layout — pure XLA on both sides,
    so the only delta is MAC reassociation (the packed weights add exact
    zero taps)."""
    from raft_stereo_tpu.models.layers import (
        apply_residual_block, apply_residual_block_packed,
        init_residual_block)
    key = jax.random.PRNGKey(3)
    p = init_residual_block(key, 64, 96, norm_fn, stride=2)
    h_, w_ = 20, 32
    x = jax.random.normal(jax.random.PRNGKey(4), (1, h_, w_, 64))
    xp = x[0].reshape(h_, w_ // 2, 2, 64).reshape(h_, w_ // 2, 128)
    ref = np.asarray(apply_residual_block(p, x, norm_fn, stride=2))
    got = np.asarray(apply_residual_block_packed(p, xp, norm_fn))
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() < 1e-5, np.abs(got - ref).max()


@pytest.mark.parametrize("norm_fn", ["batch", "instance"])
def test_fused_encoder_end_to_end_packed_layer2(norm_fn):
    """Full encoder with the fused trunk + packed layer2 entry vs the pure
    XLA chain (fused=False) — certifies the default inference path through
    layer3/heads, including the no-unpack packed handoff."""
    from raft_stereo_tpu.models.extractor import (
        apply_basic_encoder, apply_multi_basic_encoder, init_basic_encoder,
        init_multi_basic_encoder)
    key = jax.random.PRNGKey(5)
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 48, 24, 3))
    if norm_fn == "instance":
        p = init_basic_encoder(key, output_dim=256, norm_fn="instance",
                               downsample=2)
        ref = np.asarray(apply_basic_encoder(
            p, x, norm_fn="instance", downsample=2, fused=False))
        got = np.asarray(apply_basic_encoder(
            p, x, norm_fn="instance", downsample=2, fused=True))
    else:
        p = init_multi_basic_encoder(key, output_dim=[[128] * 3],
                                     norm_fn="batch", downsample=2)
        ref = np.asarray(apply_multi_basic_encoder(
            p, x, norm_fn="batch", downsample=2, num_layers=3,
            fused=False)[0][0])
        got = np.asarray(apply_multi_basic_encoder(
            p, x, norm_fn="batch", downsample=2, num_layers=3,
            fused=True)[0][0])
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() < 5e-2, np.abs(got - ref).max()


@pytest.mark.parametrize("hw,norm_fn,ch", [
    ((16, 24), "instance", 96), ((16, 24), "instance", 128),
    ((16, 24), "batch", 96), ((16, 24), "batch", 128),
    ((16, 800), "instance", 96), ((16, 800), "batch", 128),
])
def test_stream_resblock_matches_oracle(hw, norm_fn, ch, monkeypatch):
    """Streamed stride-1 residual block (raw1 -> mid1 -> point2 passes)
    vs apply_residual_block, at the tail's real channel counts (96 =
    layer2, 128 = layer3/heads). (16, 800) is the multi-strip path."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    from raft_stereo_tpu.models.layers import (
        apply_residual_block, init_residual_block)
    from raft_stereo_tpu.ops.pallas_encoder import (
        resblock_streamable, stream_resblock)
    monkeypatch.setattr(ps, "FORCE_FUSABLE_DTYPE", True)
    h_, w_ = hw
    p = init_residual_block(jax.random.PRNGKey(0), ch, ch, norm_fn, stride=1)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, h_, w_, ch))
    assert resblock_streamable(p, x, norm_fn)
    ref = np.asarray(apply_residual_block(p, x, norm_fn, stride=1))
    got = np.asarray(stream_resblock(norm_fn, p, x))
    d = np.abs(got - ref)
    assert d.max() < 5e-4, d.max()
    assert d[0].max(axis=(1, 2)).std() < d.max() + 1e-9  # diffuse, not a row


def test_stream_head_conv_matches_oracle(monkeypatch):
    """Streamed 3x3 head conv (raw output, Cout != Cin) vs apply_conv."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    from raft_stereo_tpu.models.layers import apply_conv, init_conv
    from raft_stereo_tpu.ops.pallas_encoder import (
        head_conv_streamable, stream_head_conv)
    monkeypatch.setattr(ps, "FORCE_FUSABLE_DTYPE", True)
    pc = init_conv(jax.random.PRNGKey(0), 3, 3, 128, 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 24, 40, 128))
    assert head_conv_streamable(pc, x)
    ref = np.asarray(apply_conv(pc, x, padding=1))
    got = np.asarray(stream_head_conv(pc, x))
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() < 5e-4


def test_stream_resblock_grads_match_oracle(monkeypatch):
    """custom_vjp backward == the XLA block's gradients."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    from raft_stereo_tpu.models.layers import (
        apply_residual_block, init_residual_block)
    from raft_stereo_tpu.ops.pallas_encoder import stream_resblock
    monkeypatch.setattr(ps, "FORCE_FUSABLE_DTYPE", True)
    p = init_residual_block(jax.random.PRNGKey(2), 96, 96, "instance",
                            stride=1)
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 16, 24, 96))

    def loss(fused):
        def f(p_, x_):
            out = (stream_resblock("instance", p_, x_) if fused
                   else apply_residual_block(p_, x_, "instance", stride=1))
            return jnp.sum(out * out)
        return jax.grad(f, argnums=(0, 1))(p, x)

    g_ref, gx_ref = loss(False)
    g_got, gx_got = loss(True)
    ref_leaves = jax.tree.leaves((g_ref, gx_ref))
    # Global scale: IN-cancelled bias leaves have true gradient zero, so
    # their values are rounding noise in both programs (same exclusion as
    # test_fused_train_grads_match_xla).
    gmax = max(float(np.abs(np.asarray(b)).max()) for b in ref_leaves)
    for a, b in zip(jax.tree.leaves((g_got, gx_got)), ref_leaves):
        d = np.abs(np.asarray(a) - np.asarray(b)).max()
        assert d / gmax < 1e-3, (d, gmax)


def test_streamed_tail_end_to_end_matches_xla(monkeypatch):
    """Full encoders with the streamed tail ENGAGED (layer2/layer3 second
    blocks + finest heads) vs the pure-XLA chain, both norm types."""
    import raft_stereo_tpu.ops.pallas_stream as ps
    from raft_stereo_tpu.models.extractor import (
        apply_basic_encoder, apply_multi_basic_encoder, init_basic_encoder,
        init_multi_basic_encoder)
    monkeypatch.setattr(ps, "FORCE_FUSABLE_DTYPE", True)
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(jax.random.PRNGKey(10), (1, 48, 32, 3))
    pf = init_basic_encoder(key, output_dim=256, norm_fn="instance",
                            downsample=2)
    ref = np.asarray(apply_basic_encoder(pf, x, norm_fn="instance",
                                         downsample=2, fused=False))
    got = np.asarray(apply_basic_encoder(pf, x, norm_fn="instance",
                                         downsample=2, fused=True))
    assert np.abs(got - ref).max() < 5e-2, np.abs(got - ref).max()
    pc = init_multi_basic_encoder(key, output_dim=[[128] * 3, [128] * 3],
                                  norm_fn="batch", downsample=2)
    refs = apply_multi_basic_encoder(pc, x, norm_fn="batch", downsample=2,
                                     num_layers=3, fused=False)
    gots = apply_multi_basic_encoder(pc, x, norm_fn="batch", downsample=2,
                                     num_layers=3, fused=True)
    for rlist, glist in zip(refs, gots):
        for r, g in zip(rlist, glist):
            assert np.abs(np.asarray(g) - np.asarray(r)).max() < 5e-2


def test_fused_encoder_packed_grad_matches_oracle():
    """d(loss)/d(params, x) through the packed custom_vjp == the XLA chain's
    gradients (the packed backward re-runs the oracle on the reshaped
    cotangent)."""
    from raft_stereo_tpu.models.extractor import (
        apply_basic_encoder, init_basic_encoder)
    key = jax.random.PRNGKey(7)
    p = init_basic_encoder(key, output_dim=256, norm_fn="instance",
                           downsample=2)
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 48, 24, 3))

    def loss(fused):
        def f(p_, x_):
            out = apply_basic_encoder(p_, x_, norm_fn="instance",
                                      downsample=2, fused=fused)
            return jnp.sum(out * out)
        return jax.grad(f, argnums=(0, 1))(p, x)

    g_ref, gx_ref = loss(False)
    g_got, gx_got = loss(True)
    rel = np.abs(np.asarray(gx_got) - np.asarray(gx_ref)).max() / (
        np.abs(np.asarray(gx_ref)).max() + 1e-8)
    assert rel < 5e-2, rel
    flat_ref = jax.tree_util.tree_leaves(g_ref)
    flat_got = jax.tree_util.tree_leaves(g_got)
    for a, b in zip(flat_got, flat_ref):
        d = np.abs(np.asarray(a) - np.asarray(b)).max()
        s = np.abs(np.asarray(b)).max() + 1e-8
        assert d / s < 5e-2, (d, s)


@pytest.mark.slow
def test_fused_train_grads_match_xla():
    """cfg.fused_train engages the streaming kernels in the train scan
    (with the save-kernel-outputs remat policy): the loss must sit inside
    the kernel bf16 envelope and every SIGNIFICANT gradient leaf must
    align with the XLA chain's. Bias leaves under instance norm are
    excluded — their true gradient is exactly zero (IN subtracts the
    mean), so their values are pure rounding noise in both programs.
    One iteration: with more, the bf16-divergent coordinate trajectories
    shift lookup tap positions by whole cells, which legitimately changes
    the volume (hence fnet) gradients — multi-call cotangent linearity is
    pinned separately in test_corr.py."""
    def run(fused_train):
        cfg = RAFTStereoConfig(corr_implementation="reg_tpu",
                               mixed_precision=True, fused_update=True,
                               fused_train=fused_train)
        params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(1)
        im1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 128, 3)), jnp.float32)
        im2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 128, 3)), jnp.float32)

        def loss(p):
            preds = raft_stereo_forward(p, cfg, im1, im2, iters=1,
                                        test_mode=False)
            return jnp.mean(jnp.abs(preds.astype(jnp.float32)))

        return jax.jit(jax.value_and_grad(loss))(params)

    (l0, g0), (l1, g1) = run(False), run(True)
    assert abs(l0 - l1) / abs(l0) < 0.01, (l0, l1)
    flat0 = jax.tree_util.tree_leaves_with_path(g0)
    flat1 = jax.tree_util.tree_leaves(g1)
    gmax = max(float(np.abs(np.asarray(a)).max()) for _, a in flat0)
    for (path, a), b in zip(flat0, flat1):
        a = np.asarray(a, np.float32).ravel()
        b = np.asarray(b, np.float32).ravel()
        assert np.isfinite(b).all(), path
        key = jax.tree_util.keystr(path)
        if "fnet" in key and key.endswith("['b']"):
            continue  # IN-cancelled bias: true grad is zero
        if np.abs(a).max() < 0.01 * gmax:
            continue  # insignificant leaf: noise-dominated
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
        assert cos > 0.98, (key, cos)


# ---------------------------------------------------------------------------
# r19: the resident iteration (ops/pallas_resident.py) + the B>1
# stream-batch engagement policy.


def _resident_case(key, B, hh, ww, ch, d, dtype, levels=4, radius=4):
    from raft_stereo_tpu.corr.pallas_reg import build_corr_operands
    cfg = RAFTStereoConfig(corr_levels=levels, corr_radius=radius)
    ks = jax.random.split(key, 12)
    f1 = jax.random.normal(ks[0], (B, hh, ww, d), dtype)
    f2 = jax.random.normal(ks[1], (B, hh, ww, d), dtype)
    ops = build_corr_operands(f1, f2, num_levels=levels, radius=radius,
                              out_dtype=dtype)
    coords_x = jax.random.uniform(ks[2], (B, hh, ww), jnp.float32) * ww
    flow = jnp.concatenate(
        [jax.random.normal(ks[3], (B, hh, ww, 1), dtype),
         jnp.zeros((B, hh, ww, 1), dtype)], -1)
    penc = init_motion_encoder(ks[4], cfg)
    pgru = init_conv_gru(ks[5], ch, 128 + ch)
    phead = init_flow_head(ks[6], ch, 64, 2)
    h = jax.random.normal(ks[7], (B, hh, ww, ch), dtype) * 0.5
    up = jax.random.normal(ks[8], (B, hh, ww, ch), dtype)
    ctx = tuple(jax.random.normal(k, (B, hh, ww, ch), dtype) * 0.3
                for k in ks[9:12])
    czrq = prepare_gru_context(pgru, ctx, dtype)
    return ops, coords_x, flow, penc, pgru, phead, h, up, czrq


@pytest.mark.parametrize("B,hh,ww,pack8", [
    (1, 16, 24, False),
    (2, 8, 20, False),
    (1, 8, 20, True),
    (2, 16, 18, True),  # odd-ish width: straddling tap windows
])
def test_resident_iter_bitwise_vs_serial_composition(B, hh, ww, pack8,
                                                     monkeypatch):
    """The r19 acceptance pin: the resident mega-kernel is BITWISE equal
    to the serial fused composition it replaces — standalone corr gather
    -> fused_motion -> fused_gru_head — on the same containers (bf16
    pair-packed and, when armed, int8 quad-packed)."""
    from raft_stereo_tpu.corr.pallas_reg import corr_fn_from_operands
    from raft_stereo_tpu.ops.pallas_resident import fused_iter_fwd_impl
    if pack8:
        monkeypatch.setenv("RAFT_CORR_PACK8", "1")
    dtype = jnp.bfloat16
    (ops, coords_x, flow, penc, pgru, phead, h, up,
     czrq) = _resident_case(jax.random.PRNGKey(0), B, hh, ww, 32, 16,
                            dtype)
    assert ops["pack8"] == pack8
    corr = corr_fn_from_operands(ops)(coords_x)
    motion = fused_motion_fwd_impl(penc, flow, corr)
    h_ref, dx_ref = fused_conv_gru_fwd_impl(pgru, h, czrq, motion, up,
                                            head_p=phead)
    h_got, dx_got = fused_iter_fwd_impl(penc, pgru, phead, ops, h, czrq,
                                        coords_x, flow, up)
    assert np.asarray(h_got).tobytes() == np.asarray(h_ref).tobytes()
    assert np.asarray(dx_got).tobytes() == np.asarray(dx_ref).tobytes()


def test_resident_batched_rows_match_per_sample(monkeypatch):
    """B>1 resident runs restart cleanly per sample: batched rows are
    BIT-equal to B=1 runs of the same rows (the r4 batched-kernel
    invariant, extended to the mega-kernel)."""
    from raft_stereo_tpu.ops.pallas_resident import fused_iter_fwd_impl
    dtype = jnp.bfloat16
    B = 4
    (ops, coords_x, flow, penc, pgru, phead, h, up,
     czrq) = _resident_case(jax.random.PRNGKey(1), B, 8, 20, 32, 16,
                            dtype)
    h_b, dx_b = fused_iter_fwd_impl(penc, pgru, phead, ops, h, czrq,
                                    coords_x, flow, up)
    for i in range(B):
        # Per-sample operands by slicing the batch axis (rows of the
        # volume operands are per-sample by construction).
        sliced = dict(ops)
        sliced["flat"] = [f[i:i + 1] for f in ops["flat"]]
        sliced["kernel_ops"] = [kop[i:i + 1] for kop in ops["kernel_ops"]]
        sliced["b"] = 1
        h_1, dx_1 = fused_iter_fwd_impl(
            penc, pgru, phead, sliced, h[i:i + 1], czrq[i:i + 1],
            coords_x[i:i + 1], flow[i:i + 1], up[i:i + 1])
        assert np.asarray(h_b[i:i + 1]).tobytes() == \
            np.asarray(h_1).tobytes(), f"row {i}"
        assert np.asarray(dx_b[i:i + 1]).tobytes() == \
            np.asarray(dx_1).tobytes(), f"row {i}"


def test_resident_forward_bitwise_vs_serial(monkeypatch):
    """End-to-end: the test-mode forward with the resident iteration
    engaged is bitwise equal to RAFT_FUSE_ITER=0 (the serial fused scan
    body) — segment/epilogue pins cannot move."""
    from raft_stereo_tpu.models import raft_stereo_forward
    cfg = RAFTStereoConfig(corr_implementation="reg_tpu",
                           mixed_precision=True)
    params = init_raft_stereo(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    i1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    i2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 96, 3)), jnp.float32)
    monkeypatch.setenv("RAFT_FUSE_ITER", "0")
    lo0, up0 = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                   test_mode=True)
    monkeypatch.setenv("RAFT_FUSE_ITER", "1")
    lo1, up1 = raft_stereo_forward(params, cfg, i1, i2, iters=2,
                                   test_mode=True)
    assert np.asarray(lo0).tobytes() == np.asarray(lo1).tobytes()
    assert np.asarray(up0).tobytes() == np.asarray(up1).tobytes()


def test_stream_batch_policy(monkeypatch):
    """The r19 engagement policy: B=1 unconditional; B>1 gated by the
    kill switch and the ledger-derived crossover;
    RAFT_BATCH_FUSE_PIXELS stays the explicit override."""
    import raft_stereo_tpu.ops.pallas_stream as ps

    class T:
        def __init__(self, b, h, w):
            self.shape = (b, h, w, 32)

    monkeypatch.delenv("RAFT_BATCH_FUSE_PIXELS", raising=False)
    monkeypatch.delenv("RAFT_STREAM_BATCH", raising=False)
    xo = ps.stream_batch_crossover()
    assert xo > 0
    assert ps._batch_worthwhile(T(1, 2, 2))          # B=1 always
    assert ps._batch_worthwhile(T(4, 96, 312))       # serve bucket 1/4-res
    assert not ps._batch_worthwhile(T(16, 48, 156))  # r4 regression case
    monkeypatch.setenv("RAFT_STREAM_BATCH", "0")     # kill switch
    assert not ps._batch_worthwhile(T(4, 96, 312))
    assert ps._batch_worthwhile(T(1, 2, 2))
    monkeypatch.setenv("RAFT_STREAM_BATCH", "1")
    monkeypatch.setenv("RAFT_BATCH_FUSE_PIXELS", "0")
    assert ps._batch_worthwhile(T(16, 2, 2))         # explicit always-fuse
    monkeypatch.setenv("RAFT_BATCH_FUSE_PIXELS", "1000000000")
    assert not ps._batch_worthwhile(T(2, 504, 744))  # explicit never


@pytest.mark.parametrize("B,h_,w_", [(4, 16, 24), (8, 8, 13)])
def test_stream_batch_parity_b4_b8(B, h_, w_):
    """Serve-batch geometry parity battery: B=4/8 streamed-kernel runs
    are BIT-equal to the per-sample serial loop (odd widths included) —
    what makes engaging the scheduler's device batches safe."""
    ch = 32
    key = jax.random.PRNGKey(2)
    p = init_conv_gru(key, ch, 2 * ch)
    hp = init_flow_head(jax.random.PRNGKey(9), ch, 64, 2)
    ks = jax.random.split(key, 8)
    h = jax.random.normal(ks[0], (B, h_, w_, ch)) * 0.5
    xs = [jax.random.normal(k, (B, h_, w_, ch)) for k in ks[1:3]]
    ctx = tuple(jax.random.normal(k, (B, h_, w_, ch)) * 0.3
                for k in ks[3:6])
    czrq = prepare_gru_context(p, ctx, jnp.float32)
    got, dx = fused_conv_gru_fwd_impl(p, h, czrq, *xs, head_p=hp)
    for b in range(B):
        g1, d1 = fused_conv_gru_fwd_impl(
            p, h[b:b + 1], czrq[b:b + 1], *[x[b:b + 1] for x in xs],
            head_p=hp)
        assert np.asarray(got[b:b + 1]).tobytes() == \
            np.asarray(g1).tobytes(), f"row {b}"
        assert np.asarray(dx[b:b + 1]).tobytes() == \
            np.asarray(d1).tobytes(), f"row {b}"
    cfg = RAFTStereoConfig()
    pm = init_motion_encoder(key, cfg)
    corr = jax.random.normal(key, (B, h_, w_, cfg.cor_planes))
    flow = jax.random.normal(key, (B, h_, w_, 2)).at[..., 1].set(0.0)
    gotm = fused_motion_fwd_impl(pm, flow, corr)
    for b in range(B):
        m1 = fused_motion_fwd_impl(pm, flow[b:b + 1], corr[b:b + 1])
        assert np.asarray(gotm[b:b + 1]).tobytes() == \
            np.asarray(m1).tobytes(), f"row {b}"


def test_stream_batch_any_batch_grads_match_oracle():
    """The any_batch TRAINING path at serve-like batch: custom_vjp grads
    of the batched fused GRU equal the XLA oracle's (the backward IS the
    oracle, so equality is exact up to dtype casts)."""
    from raft_stereo_tpu.ops.pallas_stream import fused_conv_gru
    ch, B = 16, 4
    key = jax.random.PRNGKey(3)
    p = init_conv_gru(key, ch, ch)
    ks = jax.random.split(key, 6)
    h = jax.random.normal(ks[0], (B, 16, 12, ch)) * 0.5
    x = jax.random.normal(ks[1], (B, 16, 12, ch))
    ctx = tuple(jax.random.normal(k, (B, 16, 12, ch)) * 0.3
                for k in ks[2:5])
    czrq = prepare_gru_context(p, ctx, jnp.float32)

    def loss_fused(h, x):
        return jnp.sum(fused_conv_gru(p, h, czrq, ctx, x) ** 2)

    def loss_oracle(h, x):
        return jnp.sum(apply_conv_gru(p, h, ctx, x) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1))(h, x)
    # The fused forward is numerically equal (fp32 interpret) so the
    # oracle-backward gradients must be tightly close to the pure-XLA
    # gradient chain.
    go = jax.grad(loss_oracle, argnums=(0, 1))(h, x)
    for a, b_ in zip(gf, go):
        assert float(jnp.max(jnp.abs(a - b_))) < 1e-3

"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the TPU-world substitute for distributed
tests). Environment must be set before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The image's sitecustomize pins JAX_PLATFORMS=axon (the TPU tunnel); override
# via config so tests always run on the 8-device virtual-CPU topology.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)

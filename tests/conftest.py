"""Test harness configuration.

Default: tests run on CPU with 8 virtual XLA devices so multi-chip
sharding logic is exercised without TPU hardware (the TPU-world
substitute for distributed tests), and every Pallas kernel runs in
interpret mode. Environment must be set before jax is imported anywhere.

``RAFT_TEST_ONCHIP=1`` keeps the real backend instead: the kernel oracle
batteries then run COMPILED through the Mosaic/XLA:TPU stack — the
one-command on-chip certification (``scripts/run_onchip_battery.sh``)
that guards the compiled-path-only regression class (r4's packed-stem
bug was invisible to interpret mode). Only the kernel_battery marker is
meant to run on-chip; the mesh tests assume the 8-device CPU topology.
"""

import os

_ONCHIP = os.environ.get("RAFT_TEST_ONCHIP", "").strip().lower() in (
    "1", "true", "yes", "on")

if not _ONCHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if not _ONCHIP:
    # The image's sitecustomize pins JAX_PLATFORMS=axon (the TPU tunnel);
    # override via config so tests run on the 8-device virtual-CPU topology.
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)

"""graftlock battery: violating/corrected fixture twins per GC checker,
the lock-order-cycle gate through the real scripts/lint.sh, LOCK_ORDER.md
drift + byte-stable regeneration, suppression/stale-meta uniformity with
the GL stage, and the runtime witness's out-of-order detection.

No jax import anywhere on these paths — the concurrency suite is AST +
stdlib threading only and must stay milliseconds-fast (the release gate
runs it before anything heavy).
"""

import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from raft_stereo_tpu.analysis.concurrency import (
    run_concurrency_analysis, write_lock_order_manifest)
from raft_stereo_tpu.analysis.concurrency.graph import (build_lock_graph,
                                                        render_manifest)
from raft_stereo_tpu.analysis.concurrency.model import LockModel
from raft_stereo_tpu.analysis.concurrency.witness import (LockWitness,
                                                          unexplained_edges)
from raft_stereo_tpu.analysis.core import Project, collect_files

pytestmark = pytest.mark.concurrency_analysis

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "raft_stereo_tpu"


def write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def gc_lint(tmp_path, files, **kw):
    """Write ``files`` (relpath -> source) under ``tmp_path`` and run the
    GC suite over it.  Manifest checking is off unless a test opts in —
    fixture trees have no committed LOCK_ORDER.md by construction."""
    write_tree(tmp_path, files)
    kw.setdefault("check_manifest", False)
    return run_concurrency_analysis([str(tmp_path)], base=str(tmp_path),
                                    **kw)


def model_of(tmp_path, files):
    write_tree(tmp_path, files)
    fs = collect_files([str(tmp_path)], base=str(tmp_path))
    return LockModel(Project(fs))


def codes(report):
    return sorted(f.code for f in report.findings)


# -- GC201: lock-order graph + manifest -------------------------------------

CYCLE_SRC = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def ba():
        with LOCK_B:
            with LOCK_A:
                pass
"""

ACYCLIC_SRC = """
    import threading

    LOCK_A = threading.Lock()
    LOCK_B = threading.Lock()

    def ab():
        with LOCK_A:
            with LOCK_B:
                pass

    def ab_again():
        with LOCK_A:
            with LOCK_B:
                pass
"""


def test_gc201_cycle_fires(tmp_path):
    rep = gc_lint(tmp_path, {"locks.py": CYCLE_SRC})
    assert "GC201" in codes(rep)
    msg = next(f for f in rep.findings if f.code == "GC201").message
    assert "lock-order cycle" in msg
    assert "LOCK_A" in msg and "LOCK_B" in msg


def test_gc201_acyclic_twin_clean_and_edge_present(tmp_path):
    rep = gc_lint(tmp_path, {"locks.py": ACYCLIC_SRC})
    assert codes(rep) == []
    m = model_of(tmp_path, {})
    edges = build_lock_graph(m)
    assert ("locks.py::LOCK_A", "locks.py::LOCK_B") in edges
    assert ("locks.py::LOCK_B", "locks.py::LOCK_A") not in edges


def test_gc201_missing_manifest_is_a_finding(tmp_path):
    rep = gc_lint(tmp_path, {"locks.py": ACYCLIC_SRC},
                  check_manifest=True)
    assert codes(rep) == ["GC201"]
    f = rep.findings[0]
    assert f.path == "LOCK_ORDER.md" and "missing" in f.message


def test_gc201_drift_and_regenerated_manifest(tmp_path):
    write_tree(tmp_path, {"locks.py": ACYCLIC_SRC})
    # a stale manifest (no edges) drifts
    (tmp_path / "LOCK_ORDER.md").write_text("# Lock order\n")
    rep = run_concurrency_analysis([str(tmp_path)], base=str(tmp_path))
    assert codes(rep) == ["GC201"]
    assert "drift" in rep.findings[0].message
    # regeneration clears it, and is byte-stable
    write_lock_order_manifest([str(tmp_path)], base=str(tmp_path))
    first = (tmp_path / "LOCK_ORDER.md").read_bytes()
    assert b"LOCK_A" in first
    rep = run_concurrency_analysis([str(tmp_path)], base=str(tmp_path))
    assert codes(rep) == []
    write_lock_order_manifest([str(tmp_path)], base=str(tmp_path))
    assert (tmp_path / "LOCK_ORDER.md").read_bytes() == first


def test_gc201_manifest_drift_is_unsuppressable(tmp_path):
    """Drift lands on LOCK_ORDER.md itself — not a python file, so no
    suppression comment can ever cover it; the only fix is regenerate
    and review."""
    write_tree(tmp_path, {"locks.py": ACYCLIC_SRC})
    (tmp_path / "LOCK_ORDER.md").write_text(
        "# graftlint: disable=GC201 (cannot apply)\n")
    rep = run_concurrency_analysis([str(tmp_path)], base=str(tmp_path))
    assert codes(rep) == ["GC201"]


# -- GC202: Future lifecycle in serve/ --------------------------------------

def test_gc202_abandoned_future_fires(tmp_path):
    rep = gc_lint(tmp_path, {"serve/svc.py": """
        from concurrent.futures import Future

        def submit():
            fut = Future()
            compute = 1
    """})
    assert codes(rep) == ["GC202"]
    assert "never resolved" in rep.findings[0].message


def test_gc202_unregistered_sink_fires(tmp_path):
    rep = gc_lint(tmp_path, {"serve/svc.py": """
        from concurrent.futures import Future

        WAITERS = []

        def submit():
            fut = Future()
            WAITERS.append(fut)
    """})
    assert codes(rep) == ["GC202"]
    assert "unregistered sink" in rep.findings[0].message


def test_gc202_risky_window_fires(tmp_path):
    rep = gc_lint(tmp_path, {"serve/svc.py": """
        from concurrent.futures import Future

        WAITERS = []

        def submit(work):
            fut = Future()
            WAITERS.append(fut)
            work()
            fut.set_result(1)
            return fut
    """})
    assert codes(rep) == ["GC202"]
    assert "can raise before it is resolved" in rep.findings[0].message


def test_gc202_corrected_twins_clean(tmp_path):
    rep = gc_lint(tmp_path, {"serve/svc.py": """
        from concurrent.futures import Future

        WAITERS = []

        def factory():
            # returned before anything can raise: the caller owns it
            fut = Future()
            return fut

        def drained(q):
            # put_nowait is the registered drain (contracts.FUTURE_DRAINS)
            fut = Future()
            q.put_nowait((0, fut))
            return fut

        def protected(work):
            # every call between escape and resolution sits under a try
            # whose handler resolves — the PR 3 exception path, fixed
            fut = Future()
            WAITERS.append(fut)
            try:
                work()
                fut.set_result(1)
            except Exception as e:
                fut.set_exception(e)
            return fut
    """})
    assert codes(rep) == []


def test_gc202_scope_is_serve_only(tmp_path):
    rep = gc_lint(tmp_path, {"util/svc.py": """
        from concurrent.futures import Future

        def submit():
            fut = Future()
            compute = 1
    """})
    assert codes(rep) == []


# -- GC203: blocking call under a held lock ---------------------------------

def test_gc203_sleep_under_lock_fires(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    time.sleep(0.1)
    """})
    assert codes(rep) == ["GC203"]
    assert "time.sleep" in rep.findings[0].message


def test_gc203_corrected_twin_clean(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    n = 1
                time.sleep(0.1)
    """})
    assert codes(rep) == []


def test_gc203_condition_wait_carveout(tmp_path):
    """cv.wait() under `with cv:` is the canonical wait pattern (wait
    releases the cv) — flagged only when OTHER locks stay held."""
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()

            def park(self):
                with self._cv:
                    self._cv.wait()
    """})
    assert codes(rep) == []
    rep = gc_lint(tmp_path, {"svc2.py": """
        import threading

        class S:
            def __init__(self):
                self._cv = threading.Condition()
                self._lock = threading.Lock()

            def park(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()
    """})
    assert codes(rep) == ["GC203"]


def test_gc203_propagated_entry_context_fires(tmp_path):
    """The cross-file half of the model: a helper whose ONLY callers
    hold the lock blocks that lock even with no lexical `with` of its
    own — the lexical-stack-only analysis GL004 could never see this."""
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    self._drain()

            def _drain(self):
                time.sleep(0.1)
    """})
    assert codes(rep) == ["GC203"]
    assert "reached via" in rep.findings[0].message


# -- GC204: sinks / IO under a held lock ------------------------------------

def test_gc204_io_under_state_lock_fires(tmp_path):
    rep = gc_lint(tmp_path, {"rec.py": """
        import json
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self.doc = {}

            def dump(self, path):
                with self._lock:
                    with open(path, "w") as f:
                        json.dump(self.doc, f)
    """})
    assert codes(rep) == ["GC204", "GC204"]  # open + json.dump


def test_gc204_snapshot_then_write_clean(tmp_path):
    rep = gc_lint(tmp_path, {"rec.py": """
        import json
        import threading

        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self.doc = {}

            def dump(self, path):
                with self._lock:
                    snap = dict(self.doc)
                with open(path, "w") as f:
                    json.dump(snap, f)
    """})
    assert codes(rep) == []


def test_gc204_dedicated_sink_lock_carveout(tmp_path):
    """A lock NAMED as an IO serializer (_sink_lock/_disk_lock) may
    cover IO — that is its whole job (the PR 7 trace-sink pattern)."""
    rep = gc_lint(tmp_path, {"rec.py": """
        import json
        import threading

        class R:
            def __init__(self):
                self._sink_lock = threading.Lock()
                self.doc = {}

            def dump(self, path):
                with self._sink_lock:
                    with open(path, "w") as f:
                        json.dump(self.doc, f)
    """})
    assert codes(rep) == []


# -- GC205: _*_locked helper discipline -------------------------------------

def test_gc205_unlocked_call_fires(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _bump_locked(self):
                self.count += 1

            def bump(self):
                self._bump_locked()
    """})
    assert codes(rep) == ["GC205"]
    assert "no lock lexically held" in rep.findings[0].message


def test_gc205_locked_call_and_chained_helper_clean(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _bump_locked(self):
                self.count += 1

            def _sweep_locked(self):
                # _*_locked -> _*_locked chains the contract
                self._bump_locked()

            def bump(self):
                with self._lock:
                    self._bump_locked()
    """})
    assert codes(rep) == []


def test_gc205_guarded_attr_mutated_lock_free_fires(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _bump_locked(self):
                self.count += 1

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def reset(self):
                self.count = 0
    """})
    assert codes(rep) == ["GC205"]
    assert "mutated lock-free" in rep.findings[0].message


def test_gc205_guarded_attr_under_lock_clean(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _bump_locked(self):
                self.count += 1

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def reset(self):
                with self._lock:
                    self.count = 0
    """})
    assert codes(rep) == []


# -- GC206: thread lifecycle in serve//obs/ ---------------------------------

def test_gc206_fire_and_forget_fires(tmp_path):
    rep = gc_lint(tmp_path, {"serve/w.py": """
        import threading

        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()
    """})
    assert codes(rep) == ["GC206"]
    assert "fire-and-forget" in rep.findings[0].message


def test_gc206_attr_thread_without_join_fires(tmp_path):
    rep = gc_lint(tmp_path, {"obs/w.py": """
        import threading

        class W:
            def start(self, fn):
                self._t = threading.Thread(target=fn)
                self._t.start()
    """})
    assert codes(rep) == ["GC206"]
    assert "no join" in rep.findings[0].message


def test_gc206_joined_twins_clean(tmp_path):
    rep = gc_lint(tmp_path, {"serve/w.py": """
        import threading

        class W:
            def start(self, fn):
                self._t = threading.Thread(target=fn)
                self._t.start()

            def stop(self):
                # snapshot-then-join (the alias idiom stop() uses
                # against concurrent restarts)
                t = self._t
                if t is not None:
                    t.join(timeout=5.0)

        def scoped(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        def handed_off(fn, reaper):
            t = threading.Thread(target=fn)
            t.start()
            reaper.adopt(t)
    """})
    assert codes(rep) == []


def test_gc206_scope_excludes_other_dirs(tmp_path):
    rep = gc_lint(tmp_path, {"util/w.py": """
        import threading

        def kick(fn):
            threading.Thread(target=fn, daemon=True).start()
    """})
    assert codes(rep) == []


# -- suppression semantics: uniform with the GL stage -----------------------

def test_gc_suppression_with_reason_applies(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    # graftlint: disable=GC203 (bounded test fixture wait)
                    time.sleep(0.1)
    """})
    assert codes(rep) == []
    assert [f.code for f in rep.suppressed] == ["GC203"]


def test_gc_suppression_without_reason_is_meta(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import threading
        import time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    time.sleep(0.1)  # graftlint: disable=GC203
    """})
    # reasonless: does NOT suppress, and is flagged (GC200 meta)
    assert codes(rep) == ["GC200", "GC203"]


def test_gc_stale_suppression_is_meta(tmp_path):
    rep = gc_lint(tmp_path, {"svc.py": """
        import time

        def poll():
            # graftlint: disable=GC203 (nothing here blocks under a lock)
            time.sleep(0.1)
    """})
    assert codes(rep) == ["GC200"]
    assert "stale" in rep.findings[0].message.lower()


def test_gc_select_filters_codes(tmp_path):
    rep = gc_lint(tmp_path, {"serve/both.py": """
        import threading
        import time
        from concurrent.futures import Future

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    time.sleep(0.1)

        def submit():
            fut = Future()
            compute = 1
    """}, select=("GC202",))
    assert codes(rep) == ["GC202"]


# -- the real scripts/lint.sh gate ------------------------------------------

def test_lint_sh_concurrency_cycle_and_corrected(tmp_path):
    """Acceptance: an injected lock-order cycle fails the REAL gate
    command; the corrected twin with a regenerated manifest passes."""
    script = REPO / "scripts" / "lint.sh"
    write_tree(tmp_path, {"locks.py": CYCLE_SRC})
    # marker so the CLI roots the manifest at the fixture dir, not REPO
    (tmp_path / "pyproject.toml").write_text("")
    res = subprocess.run(
        ["bash", str(script), "--concurrency", str(tmp_path)],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "GC201" in res.stdout and "lock-order cycle" in res.stdout
    (tmp_path / "locks.py").write_text(textwrap.dedent(ACYCLIC_SRC))
    write_lock_order_manifest([str(tmp_path)], base=str(tmp_path))
    res = subprocess.run(
        ["bash", str(script), "--concurrency", str(tmp_path)],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr


def test_write_manifest_requires_concurrency():
    res = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.analysis",
         "--write-manifest"],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 2
    assert "--write-manifest requires --concurrency" in res.stderr


# -- the live tree ----------------------------------------------------------

def test_real_tree_concurrency_clean():
    """Tier-1 pin of the ISSUE acceptance: the GC suite over the live
    package exits 0 against the committed LOCK_ORDER.md — zero
    unsuppressed findings, zero drift."""
    res = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.analysis",
         "--concurrency", str(PACKAGE)],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr


def test_committed_manifest_regeneration_is_byte_stable(tmp_path):
    """--write-manifest over the live tree reproduces the committed
    LOCK_ORDER.md byte for byte (the acceptance criterion's equality)."""
    out = tmp_path / "LOCK_ORDER.md"
    write_lock_order_manifest([str(PACKAGE)], base=str(REPO),
                              manifest_path=str(out))
    assert out.read_bytes() == (REPO / "LOCK_ORDER.md").read_bytes()


def test_release_gate_runs_graftlock_and_witness():
    gate = (REPO / "scripts" / "release_gate.sh").read_text()
    assert "--concurrency" in gate and "graftlock" in gate
    assert "check_witness.py" in gate


def test_all_gc_suppressions_carry_rationale():
    """Every GC suppression in the tree parses with a reason — the
    suite's own meta pass enforces it, this pins the current count
    stays all-reasoned (a reasonless one would fail the clean gate)."""
    res = subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.analysis",
         "--concurrency", "--json", str(PACKAGE)],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 0, res.stdout + res.stderr
    import json as _json
    doc = _json.loads(res.stdout)
    assert doc["findings"] == []


# -- the runtime witness ----------------------------------------------------

WITNESS_SRC = """\
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()


def in_order():
    with LOCK_A:
        with LOCK_B:
            pass
"""


def _witness_fixture(tmp_path):
    """A fixture module whose path LOOKS like the package (the witness
    keys lock identity on the first ``raft_stereo_tpu/`` frame), with a
    static graph containing only A -> B."""
    mod = tmp_path / "raft_stereo_tpu" / "serve" / "wit.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(WITNESS_SRC)
    fs = collect_files([str(tmp_path / "raft_stereo_tpu")],
                       base=str(tmp_path))
    return mod, LockModel(Project(fs))


def test_witness_in_order_acquisition_is_explained(tmp_path):
    mod, model = _witness_fixture(tmp_path)
    with LockWitness() as w:
        ns = {}
        exec(compile(mod.read_text(), str(mod), "exec"), ns)
        ns["in_order"]()
    assert w.edges  # the A -> B acquisition was observed...
    assert unexplained_edges(w, model) == []  # ...and is in the graph


def test_witness_detects_out_of_order_acquisition(tmp_path):
    mod, model = _witness_fixture(tmp_path)
    with LockWitness() as w:
        ns = {}
        exec(compile(mod.read_text(), str(mod), "exec"), ns)
        with ns["LOCK_B"]:
            with ns["LOCK_A"]:
                pass
    bad = unexplained_edges(w, model)
    assert len(bad) == 1
    assert "LOCK_B" in bad[0] and "LOCK_A" in bad[0]
    assert "not in the static lock-order graph" in bad[0]


def test_witness_unpatches_threading_on_exit(tmp_path):
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    with LockWitness():
        assert threading.Lock is not orig_lock
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock


def test_witness_skips_unmapped_locks(tmp_path):
    """Locks minted outside the modeled tree (stdlib, dynamic maps) map
    to no declaration and are out of scope — never a violation."""
    _mod, model = _witness_fixture(tmp_path)
    with LockWitness() as w:
        a = threading.Lock()   # minted HERE: tests/ is not in the model
        b = threading.Lock()
        with a:
            with b:
                pass
    assert unexplained_edges(w, model) == []


def test_witness_condition_wait_keeps_stack_honest(tmp_path):
    """cv.wait() fully releases the cv (even nested under another lock)
    and re-acquires on wake — the witness must not deadlock on the
    wrapped inner lock, and must re-record the re-acquisition."""
    mod = tmp_path / "raft_stereo_tpu" / "serve" / "cvfix.py"
    mod.parent.mkdir(parents=True)
    mod.write_text(textwrap.dedent("""\
        import threading

        CV = threading.Condition()
    """))
    with LockWitness() as w:
        ns = {}
        exec(compile(mod.read_text(), str(mod), "exec"), ns)
        cv = ns["CV"]
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=1.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cv:
            done.append(1)
            cv.notify()
        t.join(timeout=5.0)
        assert not t.is_alive()

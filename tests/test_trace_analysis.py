"""graftverify battery: per-GV-checker poisoned-fixture vacuity guards
(each checker must FIRE on its poison — the GL006 lesson, applied to the
tracer), the clean-tree gates, the headline ladder non-vacuity proof, and
the CLI / lint.sh wiring.

Everything traces on CPU via eval_shape/make_jaxpr/.lower() — no
execution, no TPU. The poisoned fixtures live in tests/trace_fixtures/
and are driven through the REAL CLI entry (``--trace-registry``), so the
exit-code contract (0 clean / 1 findings / 2 internal) is what is pinned.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from raft_stereo_tpu.analysis.cli import main as cli_main
from raft_stereo_tpu.analysis.knobs import ENV_KNOBS
from raft_stereo_tpu.analysis.trace import (TraceRegistry, default_registry,
                                            run_trace_analysis)
from raft_stereo_tpu.analysis.trace.checkers.gv102_ladder_vacuity import \
    LadderVacuityChecker

pytestmark = pytest.mark.trace_lint

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "trace_fixtures"

POISONS = [
    ("gv101_upcast.py", "GV101"),
    ("gv102_noop_rung.py", "GV102"),
    ("gv103_debug_print.py", "GV103"),
    ("gv104_big_const.py", "GV104"),
    ("gv105_no_donation.py", "GV105"),
]


def _load_fixture(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        f"_gvfix_{name[:-3]}", str(FIXTURES / name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build_registry()


# ---------------------------------------------------------------------------
# Poisoned-fixture vacuity guards: every checker fires, through the CLI
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fixture,code", POISONS)
def test_poisoned_fixture_exits_one(fixture, code, capsys):
    rc = cli_main(["--trace", "--trace-registry",
                   str(FIXTURES / fixture), "--json"])
    payload = json.loads(capsys.readouterr().out)
    found = {f["code"] for f in payload["findings"]}
    assert rc == 1
    assert code in found, (fixture, found)
    # The poison must fire the CHECKER, not crash the tracer.
    assert "GV000" not in found, payload["findings"]


def test_gv102_fixture_fires_both_flavors():
    rep = run_trace_analysis(_load_fixture("gv102_noop_rung.py"),
                             checkers=[LadderVacuityChecker()])
    msgs = sorted(f.message for f in rep.findings)
    assert len(msgs) == 2
    assert "IDENTICAL" in msgs[0] or "IDENTICAL" in msgs[1]  # vacuous rung
    assert any("stale-program" in m for m in msgs)           # key gap


def test_gv105_fixture_names_missing_leaves():
    rep = run_trace_analysis(_load_fixture("gv105_no_donation.py"))
    hits = [f for f in rep.findings if f.code == "GV105"]
    assert len(hits) == 1
    assert "2 of 2 donated" in hits[0].message


# ---------------------------------------------------------------------------
# Registry/suppression contract
# ---------------------------------------------------------------------------

def test_registry_suppression_with_reason(capsys):
    reg = _load_fixture("gv104_big_const.py")
    reg.suppressions[("GV104", "fixture/big_const")] = \
        "fixture: measured and accepted"
    rep = run_trace_analysis(reg)
    assert rep.ok
    assert [f.code for f in rep.suppressed] == ["GV104"]
    assert rep.suppressed[0].suppress_reason == \
        "fixture: measured and accepted"


@pytest.mark.parametrize("blank", ["", "   "])
def test_registry_reasonless_suppression_is_gv000(blank):
    reg = _load_fixture("gv104_big_const.py")
    reg.suppressions[("GV104", "fixture/big_const")] = blank
    rep = run_trace_analysis(reg)
    codes = sorted(f.code for f in rep.findings)
    assert codes == ["GV000", "GV104"]  # can't hide itself


def test_dead_entry_is_gv000_not_clean():
    from raft_stereo_tpu.analysis.trace.registry import TraceEntry

    def build():
        raise RuntimeError("entry builder exploded")
    reg = TraceRegistry(geometry="fixture",
                        entries=[TraceEntry(name="fixture/dead",
                                            build=build, env={})],
                        ladder_variants=[], knob_flips=[])
    rep = run_trace_analysis(reg)
    assert [f.code for f in rep.findings] == ["GV000"]
    assert "entry builder exploded" in rep.findings[0].message


def test_select_filter_keeps_gv000():
    reg = _load_fixture("gv104_big_const.py")
    rep = run_trace_analysis(reg, select=("GV103",))
    assert rep.findings == []  # GV104 filtered away by --select
    from raft_stereo_tpu.analysis.trace.registry import TraceEntry

    def build():
        raise RuntimeError("boom")
    dead = TraceRegistry(geometry="fixture",
                         entries=[TraceEntry(name="fixture/dead",
                                             build=build, env={})],
                         ladder_variants=[], knob_flips=[])
    rep = run_trace_analysis(dead, select=("GV103",))
    assert [f.code for f in rep.findings] == ["GV000"]  # never filterable


# ---------------------------------------------------------------------------
# Clean-tree gates + vacuity guards on the REAL registry
# ---------------------------------------------------------------------------

def test_clean_tree_small_geometry_resolves_all_entries():
    """The analyzer must resolve (build AND trace) every real entry point
    — a refactor that renames raft_stereo_prepare or reshapes the carry
    must blind graftverify loudly, not silently."""
    rep = run_trace_analysis(default_registry("small"))
    assert rep.findings == [], "\n".join(f.render() for f in rep.findings)
    assert rep.entries_traced >= 5


def test_headline_registry_structure():
    """Registry vacuity guard: the headline registry must carry the full
    ladder walk (10 rungs + untripped) and one flip probe per registered
    env knob — a knob added to ENV_KNOBS without a probe surfaces as a
    GV102 finding rather than silent shrinkage, and this pins the
    expected counts so the extraction itself can't rot."""
    reg = default_registry("headline")
    names = {e.name for e in reg.entries}
    assert {"serve/full", "serve/prepare", "serve/prepare_warm",
            "serve/segment", "serve/advance",
            "serve/epilogue", "eval/forward", "train/step"} <= names
    assert len(reg.ladder_variants) == 11  # untripped + 10 rungs
    from raft_stereo_tpu.serve.guard import DEFAULT_LADDER
    assert [label for label, _ in reg.ladder_variants[1:]] == \
        [p.name for p in DEFAULT_LADDER]
    assert len(reg.knob_flips) == len(ENV_KNOBS)
    assert all(kf.flipped is not None for kf in reg.knob_flips), \
        "every registered knob needs a KNOB_FLIP_PROBES entry"
    # Every flip must already differ in cache key (fingerprint covers
    # ENV_KNOBS); GV102's trace proves the program side.
    assert all(kf.base_key != kf.flipped_key for kf in reg.knob_flips)


def test_headline_ladder_pairwise_non_vacuous():
    """The acceptance proof, in-process: all ten breaker rungs produce
    pairwise-different programs at headline geometry (the full CLI run
    additionally proves the knob side; release_gate.sh runs it)."""
    reg = default_registry("headline")
    trimmed = TraceRegistry(geometry="headline", entries=[],
                            ladder_variants=reg.ladder_variants,
                            knob_flips=[])
    rep = run_trace_analysis(trimmed, checkers=[LadderVacuityChecker()])
    assert rep.findings == [], "\n".join(f.render() for f in rep.findings)
    assert rep.entries_traced == 11


def test_scrubbed_text_is_deterministic():
    import jax

    from raft_stereo_tpu.analysis.trace.jaxprs import scrubbed_text
    reg = default_registry("small")
    epi = next(e for e in reg.entries if e.name == "serve/epilogue")
    from raft_stereo_tpu.serve.session import _env_overrides
    with _env_overrides(dict(epi.env)):
        fn, args = epi.build()
        t1 = scrubbed_text(jax.make_jaxpr(fn)(*args))
        t2 = scrubbed_text(jax.make_jaxpr(fn)(*args))
    assert t1 == t2
    assert "0x7" not in t1  # addresses actually scrubbed


# ---------------------------------------------------------------------------
# CLI / scripts wiring
# ---------------------------------------------------------------------------

def test_cli_trace_registry_missing_is_internal_error(capsys):
    rc = cli_main(["--trace", "--trace-registry",
                   str(FIXTURES / "does_not_exist.py")])
    capsys.readouterr()
    assert rc == 2  # an internal error must never read as "clean"


def test_cli_trace_options_require_trace(capsys):
    # A poisoned registry passed WITHOUT --trace must not silently skip
    # the trace stage and exit 0 — that would read as clean.
    rc = cli_main(["--trace-registry",
                   str(FIXTURES / "gv103_debug_print.py")])
    capsys.readouterr()
    assert rc == 2
    rc = cli_main(["--trace-geometry", "small"])
    capsys.readouterr()
    assert rc == 2


def test_lint_sh_trace_stage_fails_on_poison():
    res = subprocess.run(
        ["bash", "scripts/lint.sh", "--trace", "--trace-registry",
         str(FIXTURES / "gv103_debug_print.py")],
        cwd=str(REPO), capture_output=True, text=True)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "GV103" in res.stdout


def test_release_gate_runs_graftverify_step():
    gate = (REPO / "scripts" / "release_gate.sh").read_text()
    assert "--trace --json" in gate
    assert "analysis_report.json" in gate
    # graftverify must run BEFORE the tier-1 suite (cheap gates first).
    assert gate.index('step "graftverify') < gate.index('step "tier-1')


def test_cli_list_checkers_includes_gv(capsys):
    rc = cli_main(["--list-checkers"])
    out = capsys.readouterr().out
    assert rc == 0
    for code in ("GV101", "GV102", "GV103", "GV104", "GV105"):
        assert code in out

"""Continuous-batching battery: batch-row bitwise independence, epilogue /
carry-advance composition, scheduler join/exit parity vs the PR 3
sequential path, per-row deadline degradation, EMA batch-bucket keying,
and queue backpressure under batching.

Everything runs on CPU with the tiny model config; deadlines use FakeClock
+ plan-driven slow forwards (zero real sleeping in the deadline math), and
the scheduler tests drive ``run_tick`` directly on the calling thread so
join/exit ordering is deterministic.
"""

import os
import time

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.serve.guard import CANARY_ATOL, CANARY_RTOL
from raft_stereo_tpu.models import (init_raft_stereo, raft_stereo_epilogue,
                                    raft_stereo_prepare, raft_stereo_segment,
                                    raft_stereo_segment_carry,
                                    stack_refinement_states,
                                    take_refinement_rows)
from raft_stereo_tpu.serve import (BatchScheduler, InferenceSession,
                                   ServiceConfig, SessionConfig,
                                   StereoService)
from raft_stereo_tpu.serve.validate import AdmissionConfig, validate_pair

pytestmark = pytest.mark.serve

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60  # not multiples of 32: every request really is padded

#: CROSS-BATCH-SIZE comparisons (a b=1 program's bytes vs a b>1
#: program's row) are bitwise on the reference host but drift at the
#: last-ulp level in some container XLA:CPU builds (5 documented
#: pre-existing failures, reproduced at the seed commit — CHANGES.md
#: PR 12/13).  RAFT_STRICT_BITWISE=1 keeps the strict pin (the driver's
#: host exports it); everywhere else the comparison demotes to the
#: canary drift band — the SAME band the serving canary already accepts
#: as "numerically the same program" (DESIGN.md r18).  Within-one-batch-
#: width pins stay strict bitwise unconditionally.
STRICT_BITWISE = os.environ.get("RAFT_STRICT_BITWISE", "").strip() == "1"


def assert_rows_match(got, want, what=""):
    """Cross-batch-size output comparison: bitwise under
    RAFT_STRICT_BITWISE=1, canary-band otherwise (bitwise still accepted
    first — on a clean host this never relaxes anything)."""
    got, want = np.asarray(got), np.asarray(want)
    if got.tobytes() == want.tobytes():
        return
    assert not STRICT_BITWISE, \
        f"{what}: bitwise mismatch under RAFT_STRICT_BITWISE=1"
    assert got.shape == want.shape, what
    assert np.allclose(got, want, rtol=CANARY_RTOL, atol=CANARY_ATOL), (
        f"{what}: drift exceeds the canary band "
        f"(max |d|={np.max(np.abs(got - want)):.3e})")


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pairs():
    rng = np.random.default_rng(7)
    return [(rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
             rng.uniform(0, 255, (H, W, 3)).astype(np.float32))
            for _ in range(4)]


def make_session(params, cfg, *, max_batch=4, valid_iters=4, segments=2,
                 plan=None, clock=None, **kw):
    scfg = SessionConfig(valid_iters=valid_iters, segments=segments,
                         max_batch=max_batch, canary=False, **kw)
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=clock or FakeClock())


@pytest.fixture(scope="module")
def bsession(tiny_params, tiny_cfg):
    """Shared fault-free batched session (programs accumulate across the
    read-only tests — the cache is the point of the session)."""
    return make_session(tiny_params, tiny_cfg, max_batch=4)


def canonical(pair):
    return validate_pair(pair[0], pair[1], AdmissionConfig())


def make_request(pair, rid=None, deadline=None):
    left, right = canonical(pair)
    return {"id": rid, "left": left, "right": right, "_deadline": deadline}


def drive(sched, out, n_responses, max_spins=2000):
    """Run ticks until n_responses arrived (waits out the uploader)."""
    spins = 0
    while len(out) < n_responses:
        if not sched.run_tick():
            time.sleep(0.002)
        spins += 1
        assert spins < max_spins, "scheduler made no progress"


def wait_uploaded(sched):
    """Block until every pending joiner's host->device upload finished —
    tests that pin tick-level grouping need all joiners admissible."""
    for bucket in sched._buckets.values():
        for row in list(bucket.pending):
            assert row.uploaded.wait(timeout=30)


# ---------------------------------------------------------------------------
# Model layer: composition and batch-row independence.


def test_epilogue_composes_with_segment_carry(tiny_params, tiny_cfg, pairs):
    """epilogue(segment_carry(s)) == segment(s) — the scheduler's
    advance-without-mask-head + exit-epilogue split is free of cost."""
    cfg = tiny_cfg
    i1, i2 = canonical(pairs[0])
    state = jax.jit(lambda p, a, b: raft_stereo_prepare(p, cfg, a, b))(
        tiny_params, i1, i2)
    _, low_ref, up_ref = jax.jit(
        lambda p, s: raft_stereo_segment(p, cfg, s, iters=2))(
        tiny_params, state)
    carry, dnorm = jax.jit(
        lambda p, s: raft_stereo_segment_carry(p, cfg, s, iters=2))(
        tiny_params, state)
    low, up = jax.jit(lambda p, s: raft_stereo_epilogue(p, cfg, s))(
        tiny_params, carry)
    assert np.asarray(up).tobytes() == np.asarray(up_ref).tobytes()
    assert np.asarray(low).tobytes() == np.asarray(low_ref).tobytes()
    # The convergence monitor is derived from the same endpoint coords:
    # mean |delta_x| per iteration, per row, finite and non-negative.
    dn = np.asarray(dnorm)
    assert dn.shape == (1,) and np.isfinite(dn).all() and (dn >= 0).all()


def test_batch_rows_bitwise_independent(tiny_params, tiny_cfg, pairs):
    """The invariant continuous batching stands on: a request's rows are
    byte-identical whether it runs alone, stacked with three distinct
    batchmates, or next to replicated pad rows."""
    cfg = tiny_cfg
    lefts = np.concatenate([canonical(p)[0] for p in pairs], axis=0)
    rights = np.concatenate([canonical(p)[1] for p in pairs], axis=0)
    prep = jax.jit(lambda p, a, b: raft_stereo_prepare(p, cfg, a, b))
    seg = jax.jit(lambda p, s: raft_stereo_segment(p, cfg, s, iters=2))

    sb = prep(tiny_params, lefts, rights)
    _, _, up_batch = seg(tiny_params, sb)
    for i in range(4):
        s1 = prep(tiny_params, lefts[i:i + 1], rights[i:i + 1])
        _, _, up_solo = seg(tiny_params, s1)
        # b=1 vs b=4 programs: the cross-batch-size compare (see
        # assert_rows_match — strict under RAFT_STRICT_BITWISE=1).
        assert_rows_match(up_solo, up_batch[i:i + 1], f"row {i}")
    # pad rows: row 0 advanced next to replicas of itself.  Still a
    # cross-batch-size compare (spad's carry came from a b=1 prepare,
    # up_batch's from the b=4 one), so the same demotion applies.
    spad = take_refinement_rows(prep(tiny_params, lefts[:1], rights[:1]),
                                [0, 0, 0, 0])
    _, _, up_pad = seg(tiny_params, spad)
    assert_rows_match(up_pad[:1], up_batch[:1], "pad row")


def test_stack_take_roundtrip(tiny_params, tiny_cfg, pairs):
    i1, i2 = canonical(pairs[0])
    j1, j2 = canonical(pairs[1])
    cfg = tiny_cfg
    sa = raft_stereo_prepare(tiny_params, cfg, i1, i2)
    sb = raft_stereo_prepare(tiny_params, cfg, j1, j2)
    stacked = stack_refinement_states([sa, sb])
    back_a = take_refinement_rows(stacked, [0])
    for x, y in zip(jax.tree_util.tree_leaves(back_a),
                    jax.tree_util.tree_leaves(sa)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
    with pytest.raises(ValueError):
        stack_refinement_states([])


# ---------------------------------------------------------------------------
# Session: batch buckets in keys, EMA isolation.


def test_batch_bucket_resolution_and_cache_key(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg, max_batch=6)
    assert sess.batch_buckets == (1, 2, 4, 6)
    assert sess.batch_bucket(1) == 1
    assert sess.batch_bucket(3) == 4
    assert sess.batch_bucket(6) == 6
    with pytest.raises(ValueError, match="exceeds"):
        sess.batch_bucket(7)
    # batch bucket is an explicit key component: b=1 and b=4 never share
    k1 = sess.cache_key("advance", 64, 64, 2, b=1)
    k4 = sess.cache_key("advance", 64, 64, 2, b=4)
    assert k1 != k4
    # env override, resolved once at construction
    import os
    os.environ["RAFT_BATCH_BUCKETS"] = "2,8"
    try:
        s2 = make_session(tiny_params, tiny_cfg, max_batch=8)
        assert s2.batch_buckets == (2, 8)
    finally:
        del os.environ["RAFT_BATCH_BUCKETS"]
    with pytest.raises(ValueError, match="batch_buckets"):
        SessionConfig(max_batch=4, batch_buckets=(4, 2))
    with pytest.raises(ValueError, match="max_batch"):
        SessionConfig(max_batch=0)
    # LRU floor: one fully warm shape bucket (prepare/prepare_warm/
    # advance/epilogue at every batch bucket) must fit, or warmup would
    # evict its own programs
    s8 = make_session(tiny_params, tiny_cfg, max_batch=8, max_programs=4)
    assert s8._max_programs >= 4 * len(s8.batch_buckets)


def test_ema_keyed_per_batch_bucket(tiny_params, tiny_cfg, pairs):
    """The satellite bugfix pinned: batched segments have batch-dependent
    cost, so a cold batch-4 warming invocation (which carries compile
    time) must neither poison nor even touch the batch-1 estimate."""
    clk = FakeClock()
    # ordinals: 0 prepare(warm) / 1 adv_b1(warm, excluded) / 2 adv_b1
    # (recorded) / 3 adv_b4(warm, excluded despite the huge injected
    # compile-like stall) / 4 adv_b4 (recorded)
    plan = ServeFaultPlan(slow_forwards={1: 9.0, 2: 5.0, 3: 50.0, 4: 7.0})
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, plan=plan,
                        clock=clk)
    i1, i2 = canonical(pairs[0])
    lp, rp = sess.padder_for(i1.shape).pad_np(i1, i2)
    prep = sess.get_program("prepare", 64, 64, 0, b=1)
    (state,) = sess.invoke(prep, lp, rp)
    adv1 = sess.get_program("advance", 64, 64, 2, b=1)
    state1, _, _ = sess.invoke(adv1, state)       # warming: excluded
    sess.invoke(adv1, state1)                      # recorded: 5.0
    assert sess.estimate(adv1.key) == pytest.approx(5.0)
    state4 = take_refinement_rows(state, [0, 0, 0, 0])
    adv4 = sess.get_program("advance", 64, 64, 2, b=4)
    assert adv4.key != adv1.key
    state4b, _, _ = sess.invoke(adv4, state4)      # warming: excluded
    assert sess.estimate(adv4.key) is None
    assert sess.estimate(adv1.key) == pytest.approx(5.0)  # untouched
    sess.invoke(adv4, state4b)                     # recorded: 7.0
    assert sess.estimate(adv4.key) == pytest.approx(7.0)
    assert sess.estimate(adv1.key) == pytest.approx(5.0)


# ---------------------------------------------------------------------------
# Scheduler: parity with the sequential path, join/exit boundaries,
# per-row deadlines. Ticks are driven directly — no service thread.


def test_scheduler_parity_including_pad_rows(bsession, pairs):
    """Three requests (odd count -> a pad row at batch bucket 4): every
    disparity byte-identical to the sequential session path."""
    refs = [bsession.infer(*p).disparity for p in pairs[:3]]
    out = []
    sched = BatchScheduler(bsession,
                           resolve=lambda req, resp: out.append(resp))
    for i, p in enumerate(pairs[:3]):
        sched.submit(make_request(p, rid=i))
    wait_uploaded(sched)
    drive(sched, out, 3)
    by_id = {r["id"]: r for r in out}
    for i in range(3):
        assert by_id[i]["status"] == "ok"
        assert by_id[i]["quality"] == "full"
        # scheduler rows (b=4 programs) vs the sequential b=1 reference:
        # cross-batch-size, so canary-band unless RAFT_STRICT_BITWISE=1.
        assert_rows_match(by_id[i]["disparity"], refs[i], f"request {i}")
    st = sched.status()
    assert st["joins"] == 3 and st["exits"] == 3
    assert st["pad_waste"] > 0  # 3 rows rode a 4-bucket
    assert st["occupancy_hist"].get("3") >= 1


def test_scheduler_join_exit_boundary_parity(bsession, pairs):
    """B joins the batch AFTER A already ran a segment; A exits while B
    continues — both byte-identical to their sequential runs."""
    ref_a = bsession.infer(*pairs[0]).disparity
    ref_b = bsession.infer(*pairs[1]).disparity
    out = []
    sched = BatchScheduler(bsession,
                           resolve=lambda req, resp: out.append(resp))
    sched.submit(make_request(pairs[0], rid="a"))
    wait_uploaded(sched)
    assert sched.run_tick()          # A alone: segment 1 at batch 1
    assert sched.active_rows == 1
    sched.submit(make_request(pairs[1], rid="b"))
    wait_uploaded(sched)
    assert sched.run_tick()          # B joins; A+B advance at batch 2;
    assert len(out) == 1             # A (4 iters) exits at the boundary
    assert out[0]["id"] == "a" and out[0]["quality"] == "full"
    drive(sched, out, 2)             # B's second segment + exit
    by_id = {r["id"]: r for r in out}
    # cross-batch-size (b=1/b=2 mix vs sequential): canary-band unless
    # RAFT_STRICT_BITWISE=1.
    assert_rows_match(by_id["a"]["disparity"], ref_a, "a")
    assert_rows_match(by_id["b"]["disparity"], ref_b, "b")
    st = sched.status()
    assert st["active"] == 0 and st["pending"] == 0


def test_scheduler_per_row_deadline_exit(tiny_params, tiny_cfg, pairs):
    """Per-row anytime degradation: the deadline row exits early with an
    honest reduced_iters label while its batchmate runs to full quality —
    and the batchmate's bytes don't care."""
    clk = FakeClock()
    # ordinals: 0 prepare_b2 / 1 advance_b2 (60 fake-s: blows A's budget)
    # / 2 epilogue_b1 (A's exit) / 3 advance_b1 / 4 epilogue_b1 (B)
    plan = ServeFaultPlan(slow_forwards={1: 60.0})
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, plan=plan,
                        clock=clk)
    ref_b = None  # computed after: the plan only slows ordinal 1
    out = []
    sched = BatchScheduler(sess, resolve=lambda req, resp: out.append(resp))
    sched.submit(make_request(pairs[0], rid="a", deadline=clk.now() + 50.0))
    sched.submit(make_request(pairs[1], rid="b"))
    wait_uploaded(sched)
    drive(sched, out, 2)
    by_id = {r["id"]: r for r in out}
    assert by_id["a"]["status"] == "ok"
    assert by_id["a"]["quality"] == "reduced_iters:2"
    assert by_id["a"]["iters"] == 2
    assert by_id["a"]["deadline_missed"] is True  # 60 fake-s > 50 budget
    assert by_id["b"]["quality"] == "full"
    assert np.isfinite(by_id["a"]["disparity"]).all()
    ref_b = sess.infer(*pairs[1]).disparity
    # cross-batch-size compare: canary-band unless RAFT_STRICT_BITWISE=1.
    assert_rows_match(by_id["b"]["disparity"], ref_b, "b")
    assert sess.metrics()["degraded"] == 1


def test_scheduler_deadline_estimate_stops_early(tiny_params, tiny_cfg,
                                                 pairs):
    """With a recorded per-(program, batch-bucket) estimate the policy
    exits BEFORE overrunning: reduced label, deadline_missed=False."""
    clk = FakeClock()
    # r1 (no deadline): prepare(0), adv_b1(1: warming, excluded),
    # adv_b1(2: recorded 60), epilogue(3). r2: prepare(4), adv_b1(5: 60).
    plan = ServeFaultPlan(slow_forwards={1: 60.0, 2: 60.0, 5: 60.0})
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, plan=plan,
                        clock=clk)
    out = []
    sched = BatchScheduler(sess, resolve=lambda req, resp: out.append(resp))
    sched.submit(make_request(pairs[0], rid="r1"))
    wait_uploaded(sched)
    drive(sched, out, 1)
    adv_key = sess.cache_key("advance", 64, 64, 2, b=1)
    assert sess.estimate(adv_key) == pytest.approx(60.0)
    # budget fits ONE more 60s segment plus 40s of slack — not two
    sched.submit(make_request(pairs[1], rid="r2",
                              deadline=clk.now() + 100.0))
    wait_uploaded(sched)
    drive(sched, out, 2)
    r2 = next(r for r in out if r["id"] == "r2")
    assert r2["quality"] == "reduced_iters:2"
    assert r2["deadline_missed"] is False


def test_scheduler_deadline_expired_in_queue(bsession, pairs):
    """A joiner whose deadline passed while waiting is rejected at the
    tick boundary without touching the device."""
    out = []
    sched = BatchScheduler(bsession,
                           resolve=lambda req, resp: out.append(resp))
    sched.submit(make_request(pairs[0], rid="late",
                              deadline=bsession.clock.now() - 1.0))
    wait_uploaded(sched)
    compiles = bsession.metrics()["compiles"]
    drive(sched, out, 1)
    assert out[0]["status"] == "rejected"
    assert out[0]["code"] == "deadline_exceeded_in_queue"
    assert bsession.metrics()["compiles"] == compiles


def test_scheduler_nonfinite_output_structured(tiny_params, tiny_cfg,
                                               pairs):
    """A poisoned epilogue output becomes a structured nonfinite_output
    error, never a served frame (the sequential contract, batched)."""
    # ordinals: 0 prepare / 1-2 advances / 3 epilogue (poisoned)
    plan = ServeFaultPlan(poison_outputs=(3,))
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, plan=plan)
    out = []
    sched = BatchScheduler(sess, resolve=lambda req, resp: out.append(resp))
    sched.submit(make_request(pairs[0], rid="x"))
    wait_uploaded(sched)
    drive(sched, out, 1)
    assert out[0]["status"] == "error"
    assert out[0]["code"] == "nonfinite_output"
    assert sess.metrics()["nonfinite_outputs"] == 1
    # the program itself is fine: the next request serves clean
    sched.submit(make_request(pairs[1], rid="y"))
    wait_uploaded(sched)
    drive(sched, out, 2)
    assert out[1]["status"] == "ok"


# ---------------------------------------------------------------------------
# Service integration: threads, backpressure, health, shutdown.


def test_batched_service_end_to_end(bsession, pairs):
    refs = [bsession.infer(*p).disparity for p in pairs]
    with StereoService(bsession, ServiceConfig(max_queue=8)) as svc:
        futs = [svc.submit({"id": i, "left": p[0], "right": p[1]})
                for i, p in enumerate(pairs)]
        resps = [f.result(timeout=60) for f in futs]
    for i, r in enumerate(resps):
        assert r["status"] == "ok" and r["id"] == i
        assert r["quality"] == "full"
        # batched-service rows vs sequential references: cross-batch-
        # size, canary-band unless RAFT_STRICT_BITWISE=1.
        assert_rows_match(r["disparity"], refs[i], f"request {i}")
    st = svc.status()
    assert st["requests"]["ok"] == 4
    assert st["batching"] is not None
    b = st["batching"]
    assert b["joins"] >= 4 and b["exits"] >= 4
    assert b["max_batch"] == 4
    assert b["occupancy_hist"]  # at least one tick recorded
    assert b["tick_latency_ms"]["p50"] is not None
    assert st["session"]["max_batch"] == 4


def test_batched_service_queue_full_backpressure(tiny_params, tiny_cfg,
                                                 pairs):
    """Scheduler blocked mid-tick + depth-1 queue: the third concurrent
    request gets an immediate structured queue_full rejection — the
    backpressure contract survives batching."""
    import threading

    class GateClock:
        def __init__(self):
            self.gate = threading.Event()

        @staticmethod
        def now():
            return time.monotonic()

        def sleep(self, _seconds):
            assert self.gate.wait(timeout=30)

    clk = GateClock()
    # ordinal 0 = r1's prepare, 1 = r1's first advance (gated)
    sess = make_session(tiny_params, tiny_cfg, max_batch=2, clock=clk,
                        plan=ServeFaultPlan(slow_forwards={1: 1.0}))
    svc = StereoService(sess, ServiceConfig(max_queue=1)).start()
    try:
        f1 = svc.submit({"id": 1, "left": pairs[0][0],
                         "right": pairs[0][1]})
        for _ in range(3000):  # until the scheduler is parked in the gate
            if sess.faults.forwards >= 2:
                break
            time.sleep(0.01)
        assert sess.faults.forwards >= 2
        f2 = svc.submit({"id": 2, "left": pairs[1][0],
                         "right": pairs[1][1]})
        f3 = svc.submit({"id": 3, "left": pairs[2][0],
                         "right": pairs[2][1]})
        resp3 = f3.result(timeout=5)   # rejected synchronously at submit
        clk.gate.set()
        r1 = f1.result(timeout=60)
        r2 = f2.result(timeout=60)
    finally:
        clk.gate.set()
        svc.stop()
    assert resp3["status"] == "rejected" and resp3["code"] == "queue_full"
    assert r1["status"] == "ok"
    assert r2["status"] == "ok"
    assert svc.status()["requests"]["rejected:queue_full"] == 1


def test_batched_service_restart_serves(bsession, pairs):
    """stop() then start() must serve again: each generation gets a fresh
    scheduler (the old one's uploader thread dies with it), so a
    post-restart request can never hang in a dead join queue."""
    svc = StereoService(bsession, ServiceConfig(max_queue=8))
    for generation in range(2):
        svc.start()
        r = svc.submit({"id": generation, "left": pairs[0][0],
                        "right": pairs[0][1]}).result(timeout=60)
        assert r["status"] == "ok", (generation, r)
        svc.stop()


def test_batched_service_stop_resolves_every_future(bsession, pairs):
    """stop() never abandons a Future: admitted rows finish (they own
    device state), un-admitted ones get the structured rejection."""
    svc = StereoService(bsession, ServiceConfig(max_queue=8)).start()
    futs = [svc.submit({"id": i, "left": p[0], "right": p[1]})
            for i, p in enumerate(pairs)]
    svc.stop()
    for f in futs:
        r = f.result(timeout=60)
        assert r["status"] in ("ok", "rejected")
        if r["status"] == "rejected":
            assert r["code"] in ("service_stopped", "not_running")


# ---------------------------------------------------------------------------
# r19 (graftresident): the scheduler's batched device calls ENGAGE the
# streamed kernels (previously fenced to XLA twins by the 200k-pixel
# heuristic) with responses unchanged vs the sequential path.


def _drive_scheduler(session, pairs_, n):
    out = []
    sched = BatchScheduler(session,
                           resolve=lambda req, resp: out.append(resp))
    for i, p in enumerate(pairs_[:n]):
        sched.submit(make_request(p, rid=i))
    wait_uploaded(sched)
    drive(sched, out, n)
    return {r["id"]: r for r in out}


def test_stream_batch_engaged_scheduler_parity(tiny_params, pairs,
                                               monkeypatch):
    """Batch-4 device calls with the streamed kernels ENGAGED (bf16 +
    reg_tpu + the always-fuse override): the resident mega-kernel's
    scheduler responses must be BITWISE identical to the serial fused
    kernels' at the SAME batch bucket (the r19 bit-identity contract at
    the serving layer — strict on every host: same-batch-width programs
    share every XLA stage, so only the kernels differ and they are
    pinned bitwise).

    Cross-BATCH-SIZE comparisons (engaged b=4 vs sequential b=1) are NOT
    pinned here in bf16: the b=1 and b=4 PREPARE programs differ at the
    last bf16 ulp in container XLA:CPU builds and a random-init GRU
    amplifies that chaotically per iteration (measured: the XLA twins
    drift MORE than the engaged kernels) — the existing fp32 batch-parity
    pins above stay the cross-batch-size contract, and they are
    untouched by engagement (fp32 never fuses)."""
    import jax.numpy as jnp

    import raft_stereo_tpu.ops.pallas_stream as ps

    monkeypatch.setenv("RAFT_BATCH_FUSE_PIXELS", "0")  # engage at tiny
    cfg = RAFTStereoConfig(**{**TINY, "corr_implementation": "reg_tpu",
                              "mixed_precision": True})
    # Non-vacuity: at the padded 1/4-res geometry (64x64 -> 16x16) the
    # batched hidden state must clear the engagement policy — otherwise
    # this would compare two XLA-twin runs and prove nothing.
    class _T:
        shape = (4, 16, 16, TINY["hidden_dims"][2])
        dtype = jnp.bfloat16
    assert ps._batch_worthwhile(_T)
    assert ps.gru_is_fusable(_T)

    resident = _drive_scheduler(
        make_session(tiny_params, cfg, max_batch=4), pairs, 3)
    monkeypatch.setenv("RAFT_FUSE_ITER", "0")
    serial = _drive_scheduler(
        make_session(tiny_params, cfg, max_batch=4), pairs, 3)
    for i in range(3):
        assert resident[i]["status"] == "ok"
        assert resident[i]["quality"] == "full"
        assert resident[i]["disparity"].tobytes() == \
            serial[i]["disparity"].tobytes(), (
            f"request {i}: resident scheduler response differs from the "
            "serial fused kernels at the same batch bucket")

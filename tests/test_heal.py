"""graftheal battery — the recovery plane (ISSUE 18, DESIGN.md r22).

Half-open probation for the three one-way degradation ladders, on the
injectable FakeClock so every deadline is exact and instantaneous:

- knob resolution (named ValueErrors, kill switch, explicit-config
  wins) for the six RAFT_HEAL_* pacing knobs;
- breaker rungs re-engage in STRICT REVERSE trip order, only after a
  passing parity canary run from the half-open state — a failed canary
  re-trips with doubled backoff and never touches serving state;
- a quarantined chip re-probes on the probation clock, a passing probe
  re-grows the mesh (epoch bump) with responses BITWISE identical to
  the pre-shrink serve at the same bucket and ZERO mid-request compiles
  (the warmup-LRU floor holds the re-keyed programs before any row
  routes — pinned via the deck's cumulative warm-record counter);
- the flap cap is exact: K re-admissions inside the window, then the
  chip is permanently out and never re-probed;
- ``RAFT_HEAL=0`` provably restores the one-way PR 3..17 semantics for
  all three ladders;
- fleet restart budgets refill on the decay clock: an exhausted slot
  degrades, then re-enters probation with exactly one
  handshake-verified relaunch per refund (stub instances, real
  subprocesses — the tests/test_fleet.py rig).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.serve.guard import KernelCircuitBreaker
from raft_stereo_tpu.serve.heal import (resolve_heal_backoff_max_ms,
                                        resolve_heal_backoff_ms,
                                        resolve_heal_enabled,
                                        resolve_heal_flap_cap,
                                        resolve_heal_refill_ms,
                                        resolve_heal_window_ms)
from tests.test_fleet import make_fleet
from tests.test_mesh_serve import (H, W, make_request, make_session,
                                   run_sched)
from tests.test_mesh_serve import pairs  # noqa: F401 — fixture
from tests.test_mesh_serve import tiny_cfg  # noqa: F401 — fixture
from tests.test_mesh_serve import tiny_params  # noqa: F401 — fixture

pytestmark = pytest.mark.heal

HEAL_VARS = ("RAFT_HEAL", "RAFT_HEAL_BACKOFF_MS",
             "RAFT_HEAL_BACKOFF_MAX_MS", "RAFT_HEAL_FLAP_CAP",
             "RAFT_HEAL_WINDOW_MS", "RAFT_HEAL_REFILL_MS")


@pytest.fixture(autouse=True)
def _clean_heal_env(monkeypatch):
    for var in HEAL_VARS:
        monkeypatch.delenv(var, raising=False)


def series_sum(registry, name, **labels):
    return int(sum(v for lbl, v in registry.series(name)
                   if all(lbl.get(k) == want
                          for k, want in labels.items())))


# ---------------------------------------------------------------------------
# Knob resolution (serve/heal.py): named errors, kill switch, precedence.
# ---------------------------------------------------------------------------


def test_heal_knob_resolution_named_errors(monkeypatch):
    assert resolve_heal_enabled() is True          # default ON
    monkeypatch.setenv("RAFT_HEAL", "0")
    assert resolve_heal_enabled() is False         # the kill switch
    assert resolve_heal_enabled(True) is True      # explicit config wins
    monkeypatch.setenv("RAFT_HEAL", "1")
    assert resolve_heal_enabled() is True

    assert resolve_heal_backoff_ms() == 30_000.0
    monkeypatch.setenv("RAFT_HEAL_BACKOFF_MS", "5000")
    assert resolve_heal_backoff_ms() == 5000.0
    assert resolve_heal_backoff_ms(250.0) == 250.0
    monkeypatch.setenv("RAFT_HEAL_BACKOFF_MS", "soon")
    with pytest.raises(ValueError, match="RAFT_HEAL_BACKOFF_MS"):
        resolve_heal_backoff_ms()
    monkeypatch.setenv("RAFT_HEAL_BACKOFF_MS", "-1")
    with pytest.raises(ValueError, match="RAFT_HEAL_BACKOFF_MS"):
        resolve_heal_backoff_ms()

    assert resolve_heal_backoff_max_ms() == 480_000.0
    monkeypatch.setenv("RAFT_HEAL_BACKOFF_MAX_MS", "nope")
    with pytest.raises(ValueError, match="RAFT_HEAL_BACKOFF_MAX_MS"):
        resolve_heal_backoff_max_ms()

    assert resolve_heal_flap_cap() == 2
    monkeypatch.setenv("RAFT_HEAL_FLAP_CAP", "0")
    assert resolve_heal_flap_cap() == 0            # 0 = never re-admit
    monkeypatch.setenv("RAFT_HEAL_FLAP_CAP", "-2")
    with pytest.raises(ValueError, match="RAFT_HEAL_FLAP_CAP"):
        resolve_heal_flap_cap()
    monkeypatch.setenv("RAFT_HEAL_FLAP_CAP", "many")
    with pytest.raises(ValueError, match="RAFT_HEAL_FLAP_CAP"):
        resolve_heal_flap_cap()

    assert resolve_heal_window_ms() == 600_000.0
    monkeypatch.setenv("RAFT_HEAL_WINDOW_MS", "0")
    with pytest.raises(ValueError, match="RAFT_HEAL_WINDOW_MS"):
        resolve_heal_window_ms()

    assert resolve_heal_refill_ms() == 60_000.0
    monkeypatch.setenv("RAFT_HEAL_REFILL_MS", "bad")
    with pytest.raises(ValueError, match="RAFT_HEAL_REFILL_MS"):
        resolve_heal_refill_ms()


# ---------------------------------------------------------------------------
# Breaker probation (serve/guard.py): reverse trip order, backoff
# doubling, hand-out pacing — pure state machine, no jax.
# ---------------------------------------------------------------------------


def test_breaker_probation_reverse_trip_order():
    clock = FakeClock()
    br = KernelCircuitBreaker()
    br.configure_heal(enabled=True, clock=clock, backoff_s=30.0,
                      backoff_max_s=480.0)
    br.trip("fuse_iter", "storm")
    clock.sleep(1.0)
    br.trip("corr_pack8", "storm")
    # Nothing is eligible before its probation deadline.
    assert br.heal_candidate() is None
    clock.sleep(40.0)
    # Only the MOST recently tripped rung is ever nominated — re-arming
    # fuse_iter under a still-dark corr_pack8 would canary a
    # configuration that was never served.
    assert br.heal_candidate() == "corr_pack8"
    # Hand-out pushed the deadline one backoff out: a concurrent sweep
    # cannot double-probe the rung.
    assert br.heal_candidate() is None
    assert br.untrip("corr_pack8")
    # With the later trip re-engaged, the earlier rung (deadline long
    # past) becomes the candidate — strict reverse trip order.
    assert br.heal_candidate() == "fuse_iter"
    assert br.untrip("fuse_iter")
    assert br.tripped_names == ()
    assert br.heal_candidate() is None
    assert br.heal_status()["half_open"] == {}


def test_breaker_retrip_doubles_backoff_capped():
    clock = FakeClock()
    br = KernelCircuitBreaker()
    br.configure_heal(enabled=True, clock=clock, backoff_s=30.0,
                      backoff_max_s=100.0)
    br.trip("fuse_iter", "storm")
    for want in (60.0, 100.0, 100.0):   # doubles, then pins at the cap
        br.trip("fuse_iter", "heal_canary_failed")
        st = br.heal_status()["half_open"]["fuse_iter"]
        assert st["backoff_ms"] == want * 1e3
    assert br.heal_status()["half_open"]["fuse_iter"]["retrips"] == 3
    # A pass-and-later-retrip starts back at the BASE backoff: the
    # fault class that cleared is not the one that re-trips.
    assert br.untrip("fuse_iter")
    br.trip("fuse_iter", "storm")
    assert br.heal_status()["half_open"]["fuse_iter"]["backoff_ms"] == \
        30_000.0


def test_breaker_unconfigured_keeps_one_way_semantics():
    br = KernelCircuitBreaker()
    br.trip("fuse_iter", "storm")
    assert br.heal_candidate() is None
    assert br.heal_status() == {"enabled": False, "half_open": {}}
    assert "fuse_iter" in br.tripped_names


# ---------------------------------------------------------------------------
# Session-level rung re-engagement: the canary gates the untrip.
# ---------------------------------------------------------------------------


def test_heal_breaker_canary_fail_then_pass(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg)
    base_s = sess.heal_status()["backoff_ms"] / 1e3
    sess.breaker.trip("fuse_iter", "test_injected")
    run_cfg_before = sess._run_cfg
    rebuilds0 = series_sum(sess.registry, "raft_session_rebuilds_total")
    # Not eligible before the probation deadline.
    assert sess.heal_breaker() is None
    # Poison every upcoming forward: the half-open canary must fail
    # CLOSED — the rung stays tripped and serving config is untouched.
    fwd = sess.faults.forwards
    sess.faults.plan = ServeFaultPlan(
        poison_outputs=tuple(range(fwd, fwd + 16)))
    sess.clock.sleep(base_s + 1.0)
    res = sess.heal_breaker()
    assert res == {"rung": "fuse_iter", "passed": False}
    assert "fuse_iter" in sess.breaker.tripped_names
    assert sess._run_cfg is run_cfg_before, (
        "a failed canary must never touch the serving config")
    assert series_sum(sess.registry,
                      "raft_session_rebuilds_total") == rebuilds0
    ho = sess.breaker.heal_status()["half_open"]["fuse_iter"]
    assert ho["backoff_ms"] == 2 * base_s * 1e3   # doubled on re-trip
    assert ho["probes"] == 1 and ho["retrips"] == 1
    assert sess.breaker.status()["tripped"]["fuse_iter"]["count"] == 2
    assert series_sum(sess.registry, "raft_heal_rung_probes_total",
                      rung="fuse_iter", result="failed") == 1
    # The hand-out pushed the deadline: no immediate re-probe.
    assert sess.heal_breaker() is None
    # Fault clears; after the doubled backoff the canary passes and the
    # rung re-engages (re-projected config, probation row dropped).
    sess.faults.plan = ServeFaultPlan()
    sess.clock.sleep(2 * base_s + 1.0)
    res2 = sess.heal_breaker()
    assert res2 == {"rung": "fuse_iter", "passed": True}
    assert "fuse_iter" not in sess.breaker.tripped_names
    # The re-engagement re-keyed the serving programs (one rebuild —
    # fuse_iter is an env-switch rung, so the dataclass cfg is
    # unchanged; the rebuild is what re-keys the program cache).
    assert series_sum(sess.registry,
                      "raft_session_rebuilds_total") == rebuilds0 + 1
    assert sess.breaker.heal_status()["half_open"] == {}
    assert series_sum(sess.registry, "raft_heal_rung_probes_total",
                      rung="fuse_iter", result="passed") == 1
    assert series_sum(sess.registry, "raft_heal_untrips_total",
                      rung="fuse_iter") == 1


# ---------------------------------------------------------------------------
# Mesh shrink -> re-grow: bitwise parity at the same bucket, zero
# mid-request compiles (the warmup floor held the re-keyed programs).
# ---------------------------------------------------------------------------


def test_mesh_regrow_bitwise_parity_no_midrequest_compiles(
        tiny_params, tiny_cfg, pairs):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2,
                        warmup_shapes=((H, W),))

    def reqs(tag):
        return [make_request(p, rid=f"{tag}{i}")
                for i, p in enumerate(pairs[:4])]

    want, _ = run_sched(sess, reqs("a"))
    assert all(want[f"a{i}"]["status"] == "ok" for i in range(4))
    assert sess.quarantine_chip(1)
    assert sess.mesh_chips == 1
    mid, _ = run_sched(sess, reqs("m"))
    assert all(mid[f"m{i}"]["status"] == "ok" for i in range(4))
    base_s = sess.heal_status()["backoff_ms"] / 1e3
    # Too early: the sweep must not probe.
    assert sess.heal_mesh() == {"probed": [], "readmitted": [],
                                "failed": []}
    sess.clock.sleep(base_s + 1.0)
    res = sess.heal_mesh()
    assert res == {"probed": [1], "readmitted": [1], "failed": []}
    st = sess.mesh_status()
    assert st["n_data"] == 2 and st["quarantined"] == []
    assert st["epoch"] == 2                    # shrink + re-grow
    assert series_sum(sess.registry, "raft_heal_chip_probes_total",
                      result="passed") == 1
    assert sess.heal_status()["mttr"] == {
        "last_s": pytest.approx(base_s + 1.0), "events": 1}
    # The re-admission re-warmed BEFORE returning: serving the same
    # rows at the same bucket is bitwise identical to the pre-shrink
    # run with ZERO new compile-bearing deck records (the PR 5
    # mid-request-compile class, pinned on the cumulative counter).
    warm0 = sess.deck.status()["warm_records"]
    got, _ = run_sched(sess, reqs("b"))
    for i in range(4):
        assert got[f"b{i}"]["status"] == "ok"
        assert got[f"b{i}"]["disparity"].tobytes() == \
            want[f"a{i}"]["disparity"].tobytes(), (
            f"row {i} not bitwise identical across shrink -> re-grow")
    assert sess.deck.status()["warm_records"] == warm0, (
        "the re-grown mesh served a cold program mid-request")


def test_chip_flap_cap_exact(tiny_params, tiny_cfg):
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    hs = sess.heal_status()
    base_s = hs["backoff_ms"] / 1e3
    flap_cap = hs["flap_cap"]
    assert flap_cap == 2
    # Exactly flap_cap re-admissions succeed (the backoff doubles per
    # re-quarantine, so sleep past the worst case each round).
    for k in range(flap_cap):
        assert sess.quarantine_chip(1)
        sess.clock.sleep(2 * base_s + 1.0)
        res = sess.heal_mesh()
        assert res["readmitted"] == [1], (k, res)
    assert series_sum(sess.registry,
                      "raft_heal_chips_readmitted_total") == flap_cap
    # Flap cap + 1: the chip goes PERMANENTLY out.
    assert sess.quarantine_chip(1)
    chip = sess.heal_status()["chips"]["1"]
    assert chip["permanent"] is True
    assert chip["readmissions"] == flap_cap
    assert chip["eligible_in_s"] is None
    assert series_sum(sess.registry,
                      "raft_heal_chips_permanent_total") == 1
    # Never re-probed again, no matter how long the clock runs.
    sess.clock.sleep(100 * base_s)
    assert sess.heal_mesh() == {"probed": [], "readmitted": [],
                                "failed": []}
    assert not sess.readmit_chip(1)
    st = sess.mesh_status()
    assert st["n_data"] == 1 and st["quarantined"] == [1]
    assert series_sum(sess.registry,
                      "raft_heal_chips_readmitted_total") == flap_cap


# ---------------------------------------------------------------------------
# RAFT_HEAL=0: the one-way PR 3..17 semantics, provably restored.
# ---------------------------------------------------------------------------


def test_heal_disabled_is_one_way(monkeypatch, tiny_params, tiny_cfg):
    monkeypatch.setenv("RAFT_HEAL", "0")
    sess = make_session(tiny_params, tiny_cfg, mesh_data=2)
    hs = sess.heal_status()
    assert hs["enabled"] is False
    assert hs["breaker"] == {"enabled": False, "half_open": {}}
    # Chips: quarantine arms NO probation state; no sweep, no explicit
    # readmit, no amount of clock ever re-grows the mesh.
    assert sess.quarantine_chip(1)
    sess.clock.sleep(1e6)
    assert sess.heal_mesh() == {"probed": [], "readmitted": [],
                                "failed": []}
    assert not sess.readmit_chip(1)
    assert sess.mesh_status()["quarantined"] == [1]
    assert sess.heal_status()["chips"] == {}
    # Rungs: tripped stays tripped, no candidate is ever nominated.
    sess.breaker.trip("fuse_iter", "storm")
    sess.clock.sleep(1e6)
    assert sess.heal_breaker() is None
    assert "fuse_iter" in sess.breaker.tripped_names
    assert sess.breaker.heal_status()["half_open"] == {}
    assert sess.heal_status()["mttr"] == {"last_s": None, "events": 0}


# ---------------------------------------------------------------------------
# Fleet slots (tests/test_fleet.py stub rig): restart budgets refill on
# the decay clock; a degraded slot re-enters probation.
# ---------------------------------------------------------------------------


def test_fleet_budget_refill_probation(tmp_path):
    countdown = tmp_path / "die"
    countdown.write_text("99")        # every launch dies during warmup
    extra = lambda spec: ["--die-before-ready",  # noqa: E731
                          str(countdown)]
    sup = make_fleet(n=1, budget=1, extra=extra,
                     restart_refill_ms=600_000.0)
    with sup:
        # Budget exhausted during start: the slot degraded, and the
        # ledger is visible per-slot on /fleet/healthz.
        assert sup._slots[0] is None
        doc = sup.status()
        assert doc["degraded_slots"] == 1
        row = doc["by_instance"][0]
        assert row["state"] == "degraded" and row["slot"] == 0
        assert row["restarts_spent"] == 1
        assert row["budget_remaining"] == 0
        assert doc["heal"]["enabled"] is True
        assert doc["heal"]["slot_relaunches_total"] == 0
        # No refund yet: the probation pass must NOT relaunch.
        sup.poke()
        assert sup._slots[0] is None
        # The fault clears AND the decay clock refunds a charge: the
        # next poke runs exactly one handshake-verified relaunch.
        countdown.write_text("0")
        sup.refill_s = 0.05
        time.sleep(0.12)
        sup.poke()
        assert sup._slots[0] is not None
        assert sup._slots[0].state == "ready"
        doc = sup.status()
        assert doc["degraded_slots"] == 0
        assert doc["heal"]["slot_relaunches_total"] == 1


def test_fleet_refill_disabled_stays_degraded(tmp_path):
    countdown = tmp_path / "die"
    countdown.write_text("99")
    extra = lambda spec: ["--die-before-ready",  # noqa: E731
                          str(countdown)]
    # heal=False: even a ~0 refill interval must never relaunch — the
    # one-way PR 16 semantics, bit for bit.
    sup = make_fleet(n=1, budget=1, extra=extra, heal=False,
                     restart_refill_ms=1.0)
    with sup:
        assert sup._slots[0] is None
        assert sup.status()["heal"]["enabled"] is False
        countdown.write_text("0")
        time.sleep(0.05)
        sup.poke()
        assert sup._slots[0] is None               # stays dark
        doc = sup.status()
        assert doc["degraded_slots"] == 1
        assert doc["heal"]["slot_relaunches_total"] == 0
        assert doc["by_instance"][0]["budget_remaining"] == 0

"""graftscope battery: metrics registry, span timelines, profiler hooks,
and the consolidated perf-trajectory gate (DESIGN.md "Observability
(r11)").

The serving integration tests drive the REAL stack (tiny model, CPU) on a
FakeClock with plan-driven injected device time, so every span duration is
exact and the timeline reconciliation is an equality, not a tolerance:

- a batched request's spans reconcile with its reported end-to-end
  latency (the ISSUE 7 acceptance bar: >= 6 span kinds including
  per-segment advance ticks, tiled sum == total == elapsed);
- /healthz numbers are registry reads — mutating a registry counter is
  visible in ``status()`` byte-for-byte, with no surviving ad-hoc dicts;
- the disabled-trace path is a no-op (nothing recorded, requests serve);
- the reservoir histograms that replaced the sliding-window latency
  deques stay at fixed memory under a long run;
- a synthetic out-of-band requests/s entry FAILS the trajectory gate
  through the real CLI.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.obs.metrics import Histogram, MetricsRegistry
from raft_stereo_tpu.obs.profiler import ProfilerWindow
from raft_stereo_tpu.obs.tracing import NULL_TRACE, Tracer
from raft_stereo_tpu.obs import trajectory as tj
from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                   SessionConfig, StereoService)

pytestmark = pytest.mark.obs

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(3)
    return (rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (H, W, 3)).astype(np.float32))


#: Every device invocation advances the FakeClock by this much — spans
#: get exact nonzero durations with zero real sleeping.
TICK = 0.25


def slow_plan(n: int = 64) -> ServeFaultPlan:
    return ServeFaultPlan(slow_forwards={i: TICK for i in range(n)})


def make_session(params, cfg, *, max_batch=1, valid_iters=4, segments=2,
                 plan=None, clock=None, tracer=None):
    scfg = SessionConfig(valid_iters=valid_iters, segments=segments,
                         max_batch=max_batch, canary=False)
    clock = clock or FakeClock()
    if tracer is None:
        tracer = Tracer(clock=clock, sink="")
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=clock, tracer=tracer)


# ---------------------------------------------------------------------------
# Metrics registry.


def test_registry_get_or_create_and_type_conflict():
    r = MetricsRegistry()
    c1 = r.counter("x_total", "help", k="a")
    c2 = r.counter("x_total", k="a")
    assert c1 is c2
    assert r.counter("x_total", k="b") is not c1
    with pytest.raises(ValueError):
        r.gauge("x_total")
    with pytest.raises(ValueError):
        r.counter("bad name")


def test_counter_monotonic():
    r = MetricsRegistry()
    c = r.counter("c_total")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_reservoir_memory_stays_flat():
    """The satellite pin: the histograms replacing the sliding-window
    latency lists hold FIXED memory under a long run — in both modes."""
    for mode in ("window", "reservoir"):
        h = Histogram("h", (), size=512, mode=mode)
        for i in range(20_000):
            h.observe(float(i % 997))
        assert h.count == 20_000
        assert h.n == 512
        assert len(h._sample) == 512  # the actual buffer, not a view
        assert h.percentile(0.5) is not None
        assert 0 <= h.percentile(0.99) <= 996


def test_window_histogram_tracks_recent_regression():
    """The latency instruments sample the newest N (the old deque
    semantics): after a regression, percentiles move immediately — a
    lifetime-uniform reservoir would dilute it to invisibility."""
    h = Histogram("h", (), size=64, mode="window")
    for _ in range(10_000):
        h.observe(0.01)          # long healthy history
    for _ in range(64):
        h.observe(1.0)           # fresh regression
    assert h.percentile(0.5) == 1.0
    assert sorted(h._sample) == [1.0] * 64


def test_histogram_percentile_matches_legacy_formula():
    """Same formula the pre-registry deques used — /healthz p50/p99
    cannot shift at equal sample counts."""
    h = Histogram("h", (), size=64)
    vals = [0.5, 0.1, 0.9, 0.3, 0.7]
    for v in vals:
        h.observe(v)
    lat = sorted(vals)
    for p in (0.5, 0.99):
        assert h.percentile(p) == lat[min(len(lat) - 1, int(p * len(lat)))]


def test_metrics_prometheus_golden():
    r = MetricsRegistry()
    r.counter("test_requests_total", "served", outcome="ok").inc(3)
    r.counter("test_requests_total", outcome="rejected:queue_full").inc()
    r.gauge("test_queue_depth", "depth").set(2)
    h = r.histogram("test_latency_seconds", "lat", reservoir=8)
    for v in (1, 2, 3, 4):
        h.observe(v)
    golden = """\
# HELP test_latency_seconds lat
# TYPE test_latency_seconds summary
test_latency_seconds{quantile="0.5"} 3
test_latency_seconds{quantile="0.9"} 4
test_latency_seconds{quantile="0.99"} 4
test_latency_seconds_sum 10
test_latency_seconds_count 4
# HELP test_queue_depth depth
# TYPE test_queue_depth gauge
test_queue_depth 2
# HELP test_requests_total served
# TYPE test_requests_total counter
test_requests_total{outcome="ok"} 3
test_requests_total{outcome="rejected:queue_full"} 1
"""
    assert r.render_prometheus() == golden


# ---------------------------------------------------------------------------
# Tracing (unit level).


def test_trace_tiling_and_summary():
    clk = FakeClock()
    tr = Tracer(clock=clk, sink="")
    t = tr.start_request("r")
    t.mark("admission")
    clk.sleep(0.5)
    t.mark("queue_wait")
    with t.span("prepare"):
        clk.sleep(0.25)
    t.add_span("upload", 0.0, 0.4, concurrent=True)
    t.event("breaker_trip", rung="corr_kernel")
    t.finish(status="ok", quality="full")
    doc = tr.last()
    s = doc["summary"]
    assert s["total_ms"] == pytest.approx(750.0)
    assert s["tiled_ms"] == pytest.approx(750.0)  # concurrent excluded
    assert s["kinds"]["upload"]["ms"] == pytest.approx(400.0)
    assert doc["meta"] == {"status": "ok", "quality": "full"}
    # finish is idempotent: a second resolution cannot double-record
    t.finish(status="error")
    assert len(tr.timelines()) == 1
    assert tr.last()["meta"]["status"] == "ok"


def test_tracer_jsonl_sink(tmp_path, monkeypatch):
    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("RAFT_TRACE", str(path))
    clk = FakeClock()
    tr = Tracer(clock=clk)  # picks the sink up from RAFT_TRACE
    for i in range(2):
        t = tr.start_request(f"r{i}")
        clk.sleep(0.1)
        t.mark("queue_wait")
        t.finish(status="ok")
    tr.close()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [d["request_id"] for d in lines] == ["r0", "r1"]
    assert lines[0]["spans"][0]["kind"] == "queue_wait"
    assert lines[0]["total_ms"] == pytest.approx(100.0)


def test_tracer_sink_failure_never_raises(tmp_path):
    """Telemetry must never take serving down: a bad sink path (or a
    disk-full mid-run) disables the sink and keeps the ring recording —
    an escaped exception here would kill the scheduler thread and hang
    every pending Future."""
    clk = FakeClock()
    tr = Tracer(clock=clk, sink=str(tmp_path / "no_such_dir" / "t.jsonl"))
    t = tr.start_request("r0")
    t.finish(status="ok")  # must not raise
    assert tr.status()["sink"] is None  # sink dropped
    t2 = tr.start_request("r1")
    t2.finish(status="ok")
    assert len(tr.timelines()) == 2  # ring unaffected


def test_disabled_tracer_is_noop():
    tr = Tracer(clock=FakeClock(), enabled=False, sink="")
    t = tr.start_request("x")
    assert t is NULL_TRACE
    t.mark("a")
    with t.span("b"):
        pass
    t.event("c")
    t.finish()
    assert tr.timelines() == []
    assert tr.status()["recorded"] == 0


# ---------------------------------------------------------------------------
# Profiler hooks.


def test_profiler_disabled_without_dir(monkeypatch):
    monkeypatch.delenv("RAFT_PROFILE_DIR", raising=False)
    p = ProfilerWindow()
    assert not p.enabled
    assert p.start() is False  # recorded no-op, never raises
    assert p.stop() is None
    assert p.status()["refused"] == 1


def test_profiler_window_counts(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    p = ProfilerWindow(out_dir=str(tmp_path))
    assert p.start() is True
    assert p.start() is False  # serialized: refuse a nested window
    assert p.stop() == str(tmp_path)
    assert p.stop() is None    # double stop: loser is a no-op
    with p.window() as opened:
        assert opened is True
    assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]
    st = p.status()
    assert st["windows"] == 2 and st["active"] is False


def test_session_reads_profile_dir_env(tmp_path, monkeypatch, tiny_params,
                                       tiny_cfg):
    monkeypatch.setenv("RAFT_PROFILE_DIR", str(tmp_path))
    sess = make_session(tiny_params, tiny_cfg)
    assert sess.profiler.enabled
    assert sess.status()["profiler"]["dir"] == str(tmp_path)


# ---------------------------------------------------------------------------
# Trajectory gate.


def test_trajectory_emit_namespaces_and_appends(tmp_path):
    path = str(tmp_path / "traj.json")
    tj.emit("m1", 10.0, "requests/s", backend="cpu", path=path)
    tj.emit("m2", 1.0, "frames/s", backend="tpu", path=path)
    doc = tj.load(path)
    assert [e["metric"] for e in doc["entries"]] == ["cpu:m1", "m2"]


def test_trajectory_emit_noop_without_target(tmp_path, monkeypatch):
    monkeypatch.delenv("RAFT_TRAJECTORY", raising=False)
    assert tj.emit("m", 1.0, "u") is None


def test_trajectory_check_bands():
    doc = {"schema": 1, "entries": [
        {"metric": "rps", "value": 8.0, "unit": "requests/s"},
        {"metric": "unpinned", "value": 1.0, "unit": "x"}]}
    bands = {"schema": 1,
             "bands": {"rps": {"value": 10.0, "rel_band": 0.2}}}
    res = tj.check(doc, bands)
    assert res.ok and res.checked == 1 and res.unpinned == ["unpinned"]
    doc["entries"][0]["value"] = 7.9  # below 10 * 0.8
    res = tj.check(doc, bands)
    assert not res.ok and "rps" in res.failures[0]
    doc["entries"][0]["value"] = 13.0  # above band: a note, never a fail
    res = tj.check(doc, bands)
    assert res.ok and res.notes


def test_trajectory_min_only_band_and_malformed_band():
    doc = {"schema": 1, "entries": [
        {"metric": "m", "value": 5.0, "unit": "x"}]}
    # min-only band: a legal explicit floor (no pinned center, no notes)
    bands = {"schema": 1, "bands": {"m": {"min": 1.0}}}
    res = tj.check(doc, bands)
    assert res.ok and res.checked == 1 and not res.notes
    doc["entries"][0]["value"] = 0.5
    res = tj.check(doc, bands)
    assert not res.ok and "explicit min" in res.failures[0]
    # a band with neither value nor min is malformed -> internal error
    # (exit 2 via the CLI), never a silent pass
    with pytest.raises(tj.TrajectoryError):
        tj.check(doc, {"schema": 1, "bands": {"m": {"rel_band": 0.2}}})


def test_trajectory_autopin_never_overwrites():
    doc = {"schema": 1, "entries": [
        {"metric": "a", "value": 5.0, "unit": "x"},
        {"metric": "b", "value": 2.0, "unit": "x"},
        {"metric": "cpu:c", "value": 9.0, "unit": "x"}]}
    bands = {"schema": 1, "bands": {"a": {"value": 4.0, "rel_band": 0.2}}}
    pinned = tj.autopin(doc, bands)
    assert pinned == ["b"]                       # a existed, cpu:c skipped
    assert bands["bands"]["a"]["value"] == 4.0   # untouched
    assert bands["bands"]["b"]["value"] == 2.0


def _traj_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.obs.trajectory"] + args,
        capture_output=True, text=True)


def test_trajectory_gate_cli_fails_out_of_band(tmp_path):
    """ISSUE 7 acceptance: a synthetic out-of-band requests/s entry fails
    the gate through the real CLI (the exact command release_gate.sh
    runs)."""
    traj = tmp_path / "TRAJECTORY.json"
    bands = tmp_path / "bands.json"
    traj.write_text(json.dumps({"schema": 1, "entries": [
        {"metric": "serve_requests_per_s_tiny", "value": 3.0,
         "unit": "requests/s", "source": "scratch/bench_serve.py"}]}))
    bands.write_text(json.dumps({"schema": 1, "bands": {
        "serve_requests_per_s_tiny": {"value": 10.0, "rel_band": 0.2}}}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "below the pinned floor" in res.stdout
    # in-band value passes the same gate
    traj.write_text(json.dumps({"schema": 1, "entries": [
        {"metric": "serve_requests_per_s_tiny", "value": 9.5,
         "unit": "requests/s"}]}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 0, res.stdout + res.stderr


def test_trajectory_gate_cli_malformed_is_rc2(tmp_path):
    traj = tmp_path / "TRAJECTORY.json"
    traj.write_text("{not json")
    res = _traj_cli(["check", str(traj), "--bands",
                     str(tmp_path / "missing_bands.json")])
    assert res.returncode == 2  # can never read as "clean"


# ---------------------------------------------------------------------------
# Serving integration: the batched span timeline (acceptance bar).


def test_batched_request_span_timeline_reconciles(tiny_params, tiny_cfg,
                                                  pair):
    """One request through the batched scheduler: >= 6 span kinds incl.
    per-segment advance ticks; tiled span sum == trace total == reported
    end-to-end latency, exactly, under FakeClock."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        valid_iters=4, segments=2, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        resp = svc.submit({"id": "r0", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    doc = sess.tracer.last()
    assert doc["meta"]["status"] == "ok"
    kinds = doc["summary"]["kinds"]
    # admission, queue_wait, upload, prepare, advance, epilogue, unpad
    assert set(kinds) >= {"admission", "queue_wait", "upload", "prepare",
                          "advance", "epilogue", "unpad"}
    assert kinds["advance"]["count"] == 2          # one per segment tick
    # prepare + 2 advances + epilogue, TICK injected device time each
    assert resp["elapsed_ms"] == pytest.approx(4 * TICK * 1e3)
    assert doc["summary"]["tiled_ms"] == pytest.approx(
        doc["summary"]["total_ms"])
    assert doc["summary"]["total_ms"] == pytest.approx(resp["elapsed_ms"])


def test_batched_deadline_exit_records_degrade_event(tiny_params, tiny_cfg,
                                                     pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        valid_iters=4, segments=2, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        # Warm + seed the EMAs (first request's invokes are warming runs).
        assert svc.submit({"id": "w0", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
        assert svc.submit({"id": "w1", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
        # Budget fits prepare + ONE advance (0.5 s) but not a second
        # (EMA predicts 0.25 * 1.15 overshoot past 0.6).
        resp = svc.submit({"id": "d", "left": pair[0], "right": pair[1],
                           "deadline_ms": 600.0}).result(timeout=120)
    assert resp["status"] == "ok"
    assert resp["quality"] == "reduced_iters:2"
    doc = sess.tracer.last()
    assert doc["meta"]["quality"] == "reduced_iters:2"
    kinds = doc["summary"]["kinds"]
    assert kinds["advance"]["count"] == 1
    assert "degrade" in kinds
    degrade = [s for s in doc["spans"] if s["kind"] == "degrade"][0]
    assert degrade["attrs"]["label"] == "reduced_iters:2"
    assert degrade["attrs"]["reason"] == "predicted_overshoot"


def test_sequential_request_span_timeline(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=4, workers=1)) as svc:
        resp = svc.submit({"id": "s0", "left": pair[0], "right": pair[1],
                           "deadline_ms": 60_000.0}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    doc = sess.tracer.last()
    kinds = doc["summary"]["kinds"]
    assert set(kinds) >= {"admission", "queue_wait", "prepare", "segment",
                          "unpad"}
    assert kinds["segment"]["count"] == 2
    assert doc["summary"]["tiled_ms"] == pytest.approx(
        doc["summary"]["total_ms"])
    assert doc["summary"]["total_ms"] == pytest.approx(resp["elapsed_ms"])


def test_disabled_tracing_serves_and_records_nothing(tiny_params, tiny_cfg,
                                                     pair):
    clock = FakeClock()
    tracer = Tracer(clock=clock, enabled=False, sink="")
    sess = make_session(tiny_params, tiny_cfg, clock=clock, tracer=tracer)
    with StereoService(sess, ServiceConfig(max_queue=4)) as svc:
        resp = svc.submit({"id": "n", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"
    assert tracer.timelines() == []


# ---------------------------------------------------------------------------
# /healthz derives from the registry (no surviving ad-hoc dicts).


def test_healthz_is_registry_derived(tiny_params, tiny_cfg, pair):
    sess = make_session(tiny_params, tiny_cfg)
    svc = StereoService(sess, ServiceConfig(max_queue=4))
    with svc:
        assert svc.submit({"id": "h", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
    st = svc.status()
    assert st["requests"]["ok"] == 1
    assert st["latency_ms"]["n"] == 1
    # Byte-for-byte: a registry mutation IS a /healthz mutation — there is
    # no second store the document could be reading.
    svc.registry.counter("raft_requests_total", outcome="ok").inc(41)
    assert svc.status()["requests"]["ok"] == 42
    sess.registry.counter("raft_session_requests_ok_total").inc(9)
    assert sess.metrics()["requests_ok"] == 10
    assert st["session"]["counts"]["requests_ok"] == 1  # pre-mutation copy
    # the legacy ad-hoc stores are gone
    assert not hasattr(svc, "_counts") and not hasattr(svc, "_latencies")
    assert not hasattr(sess, "_metrics")


def test_metrics_text_covers_all_subsystems(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        assert svc.submit({"id": "m", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
    # After stop() the scheduler is quiesced: the registry is stable, and
    # /metrics keeps answering (scrapes outlive the worker threads).
    text = svc.metrics_text()
    assert '# TYPE raft_requests_total counter' in text
    assert 'raft_requests_total{outcome="ok"} 1' in text
    assert "raft_session_compiles_total" in text
    assert "raft_sched_ticks_total" in text
    assert "# TYPE raft_request_latency_seconds summary" in text
    assert "raft_program_calls_total" in text
    # scheduler /healthz numbers equal the rendered series
    b = svc.status()["batching"]
    assert f"raft_sched_ticks_total {b['ticks']}" in text


def test_program_device_host_split_recorded(tiny_params, tiny_cfg, pair):
    """Per-program-kind device-vs-host time: the injected device time
    lands in the device counter of the kind that ran it (steady-state
    invocations only; warmups are compile-inclusive and binned apart)."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    # Two identical requests: the first warms, the second is steady.
    sess.infer(*[p[None] for p in pair])
    sess.infer(*[p[None] for p in pair])
    dev = sess.registry.value("raft_program_device_seconds_total",
                              kind="full")
    warm = sess.registry.value("raft_program_warmup_seconds_total",
                               kind="full")
    assert dev == pytest.approx(TICK)   # one steady invocation
    assert warm == pytest.approx(TICK)  # one warming invocation
    assert sess.registry.value("raft_program_calls_total", kind="full") == 2


def test_breaker_trip_counter_in_registry(tiny_params, tiny_cfg, pair):
    from raft_stereo_tpu.faults import ServeFaultPlan
    plan = ServeFaultPlan(compile_errors={0: "mosaic:gru1632"})
    sess = make_session(tiny_params, tiny_cfg, plan=plan)
    sess.infer(*[p[None] for p in pair])  # walks one rung, then serves
    assert sess.registry.value("raft_breaker_trips_total",
                               rung="fuse_gru1632",
                               reason="compile_failure") == 1
    doc = sess.tracer.last()
    assert doc is None  # direct session.infer without a service trace

"""graftscope battery: metrics registry, span timelines, profiler hooks,
and the consolidated perf-trajectory gate (DESIGN.md "Observability
(r11)").

The serving integration tests drive the REAL stack (tiny model, CPU) on a
FakeClock with plan-driven injected device time, so every span duration is
exact and the timeline reconciliation is an equality, not a tolerance:

- a batched request's spans reconcile with its reported end-to-end
  latency (the ISSUE 7 acceptance bar: >= 6 span kinds including
  per-segment advance ticks, tiled sum == total == elapsed);
- /healthz numbers are registry reads — mutating a registry counter is
  visible in ``status()`` byte-for-byte, with no surviving ad-hoc dicts;
- the disabled-trace path is a no-op (nothing recorded, requests serve);
- the reservoir histograms that replaced the sliding-window latency
  deques stay at fixed memory under a long run;
- a synthetic out-of-band requests/s entry FAILS the trajectory gate
  through the real CLI.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import jax

from raft_stereo_tpu.config import RAFTStereoConfig
from raft_stereo_tpu.faults import FakeClock, ServeFaultPlan
from raft_stereo_tpu.models import init_raft_stereo
from raft_stereo_tpu.obs import ledger as lg
from raft_stereo_tpu.obs.flight import FlightRecorder
from raft_stereo_tpu.obs.metrics import Histogram, MetricsRegistry
from raft_stereo_tpu.obs.profiler import ProfilerWindow
from raft_stereo_tpu.obs.tracing import NULL_TRACE, Tracer
from raft_stereo_tpu.obs import trajectory as tj
from raft_stereo_tpu.serve import (InferenceSession, ServiceConfig,
                                   SessionConfig, StereoService)

pytestmark = pytest.mark.obs

TINY = dict(n_gru_layers=1, hidden_dims=(32, 32, 32),
            corr_levels=2, corr_radius=2)
H, W = 40, 60


@pytest.fixture(scope="module")
def tiny_cfg():
    return RAFTStereoConfig(**TINY)


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_raft_stereo(jax.random.PRNGKey(0), tiny_cfg)


@pytest.fixture(scope="module")
def pair():
    rng = np.random.default_rng(3)
    return (rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
            rng.uniform(0, 255, (H, W, 3)).astype(np.float32))


#: Every device invocation advances the FakeClock by this much — spans
#: get exact nonzero durations with zero real sleeping.
TICK = 0.25


def slow_plan(n: int = 64) -> ServeFaultPlan:
    return ServeFaultPlan(slow_forwards={i: TICK for i in range(n)})


def make_session(params, cfg, *, max_batch=1, valid_iters=4, segments=2,
                 plan=None, clock=None, tracer=None, flight=None,
                 max_programs=8):
    scfg = SessionConfig(valid_iters=valid_iters, segments=segments,
                         max_batch=max_batch, canary=False,
                         max_programs=max_programs)
    clock = clock or FakeClock()
    if tracer is None:
        tracer = Tracer(clock=clock, sink="")
    return InferenceSession(params, cfg, scfg, fault_plan=plan,
                            clock=clock, tracer=tracer, flight=flight)


# ---------------------------------------------------------------------------
# Metrics registry.


def test_registry_get_or_create_and_type_conflict():
    r = MetricsRegistry()
    c1 = r.counter("x_total", "help", k="a")
    c2 = r.counter("x_total", k="a")
    assert c1 is c2
    assert r.counter("x_total", k="b") is not c1
    with pytest.raises(ValueError):
        r.gauge("x_total")
    with pytest.raises(ValueError):
        r.counter("bad name")


def test_counter_monotonic():
    r = MetricsRegistry()
    c = r.counter("c_total")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_reservoir_memory_stays_flat():
    """The satellite pin: the histograms replacing the sliding-window
    latency lists hold FIXED memory under a long run — in both modes."""
    for mode in ("window", "reservoir"):
        h = Histogram("h", (), size=512, mode=mode)
        for i in range(20_000):
            h.observe(float(i % 997))
        assert h.count == 20_000
        assert h.n == 512
        assert len(h._sample) == 512  # the actual buffer, not a view
        assert h.percentile(0.5) is not None
        assert 0 <= h.percentile(0.99) <= 996


def test_window_histogram_tracks_recent_regression():
    """The latency instruments sample the newest N (the old deque
    semantics): after a regression, percentiles move immediately — a
    lifetime-uniform reservoir would dilute it to invisibility."""
    h = Histogram("h", (), size=64, mode="window")
    for _ in range(10_000):
        h.observe(0.01)          # long healthy history
    for _ in range(64):
        h.observe(1.0)           # fresh regression
    assert h.percentile(0.5) == 1.0
    assert sorted(h._sample) == [1.0] * 64


def test_histogram_percentile_matches_legacy_formula():
    """Same formula the pre-registry deques used — /healthz p50/p99
    cannot shift at equal sample counts."""
    h = Histogram("h", (), size=64)
    vals = [0.5, 0.1, 0.9, 0.3, 0.7]
    for v in vals:
        h.observe(v)
    lat = sorted(vals)
    for p in (0.5, 0.99):
        assert h.percentile(p) == lat[min(len(lat) - 1, int(p * len(lat)))]


def test_metrics_prometheus_golden():
    r = MetricsRegistry()
    r.counter("test_requests_total", "served", outcome="ok").inc(3)
    r.counter("test_requests_total", outcome="rejected:queue_full").inc()
    r.gauge("test_queue_depth", "depth").set(2)
    h = r.histogram("test_latency_seconds", "lat", reservoir=8)
    for v in (1, 2, 3, 4):
        h.observe(v)
    golden = """\
# HELP test_latency_seconds lat
# TYPE test_latency_seconds summary
test_latency_seconds{quantile="0.5"} 3
test_latency_seconds{quantile="0.9"} 4
test_latency_seconds{quantile="0.99"} 4
test_latency_seconds_sum 10
test_latency_seconds_count 4
# HELP test_queue_depth depth
# TYPE test_queue_depth gauge
test_queue_depth 2
# HELP test_requests_total served
# TYPE test_requests_total counter
test_requests_total{outcome="ok"} 3
test_requests_total{outcome="rejected:queue_full"} 1
"""
    assert r.render_prometheus() == golden


# ---------------------------------------------------------------------------
# Tracing (unit level).


def test_trace_tiling_and_summary():
    clk = FakeClock()
    tr = Tracer(clock=clk, sink="")
    t = tr.start_request("r")
    t.mark("admission")
    clk.sleep(0.5)
    t.mark("queue_wait")
    with t.span("prepare"):
        clk.sleep(0.25)
    t.add_span("upload", 0.0, 0.4, concurrent=True)
    t.event("breaker_trip", rung="corr_kernel")
    t.finish(status="ok", quality="full")
    doc = tr.last()
    s = doc["summary"]
    assert s["total_ms"] == pytest.approx(750.0)
    assert s["tiled_ms"] == pytest.approx(750.0)  # concurrent excluded
    assert s["kinds"]["upload"]["ms"] == pytest.approx(400.0)
    assert doc["meta"] == {"status": "ok", "quality": "full"}
    # finish is idempotent: a second resolution cannot double-record
    t.finish(status="error")
    assert len(tr.timelines()) == 1
    assert tr.last()["meta"]["status"] == "ok"


def test_tracer_jsonl_sink(tmp_path, monkeypatch):
    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("RAFT_TRACE", str(path))
    clk = FakeClock()
    tr = Tracer(clock=clk)  # picks the sink up from RAFT_TRACE
    for i in range(2):
        t = tr.start_request(f"r{i}")
        clk.sleep(0.1)
        t.mark("queue_wait")
        t.finish(status="ok")
    tr.close()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [d["request_id"] for d in lines] == ["r0", "r1"]
    assert lines[0]["spans"][0]["kind"] == "queue_wait"
    assert lines[0]["total_ms"] == pytest.approx(100.0)


def test_tracer_sink_failure_never_raises(tmp_path):
    """Telemetry must never take serving down: a bad sink path (or a
    disk-full mid-run) disables the sink and keeps the ring recording —
    an escaped exception here would kill the scheduler thread and hang
    every pending Future."""
    clk = FakeClock()
    tr = Tracer(clock=clk, sink=str(tmp_path / "no_such_dir" / "t.jsonl"))
    t = tr.start_request("r0")
    t.finish(status="ok")  # must not raise
    assert tr.status()["sink"] is None  # sink dropped
    t2 = tr.start_request("r1")
    t2.finish(status="ok")
    assert len(tr.timelines()) == 2  # ring unaffected


def test_disabled_tracer_is_noop():
    tr = Tracer(clock=FakeClock(), enabled=False, sink="")
    t = tr.start_request("x")
    assert t is NULL_TRACE
    t.mark("a")
    with t.span("b"):
        pass
    t.event("c")
    t.finish()
    assert tr.timelines() == []
    assert tr.status()["recorded"] == 0


# ---------------------------------------------------------------------------
# Profiler hooks.


def test_profiler_disabled_without_dir(monkeypatch):
    monkeypatch.delenv("RAFT_PROFILE_DIR", raising=False)
    p = ProfilerWindow()
    assert not p.enabled
    assert p.start() is False  # recorded no-op, never raises
    assert p.stop() is None
    assert p.status()["refused"] == 1


def test_profiler_window_counts(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))
    p = ProfilerWindow(out_dir=str(tmp_path))
    assert p.start() is True
    assert p.start() is False  # serialized: refuse a nested window
    assert p.stop() == str(tmp_path)
    assert p.stop() is None    # double stop: loser is a no-op
    with p.window() as opened:
        assert opened is True
    assert [c[0] for c in calls] == ["start", "stop", "start", "stop"]
    st = p.status()
    assert st["windows"] == 2 and st["active"] is False


def test_session_reads_profile_dir_env(tmp_path, monkeypatch, tiny_params,
                                       tiny_cfg):
    monkeypatch.setenv("RAFT_PROFILE_DIR", str(tmp_path))
    sess = make_session(tiny_params, tiny_cfg)
    assert sess.profiler.enabled
    assert sess.status()["profiler"]["dir"] == str(tmp_path)


# ---------------------------------------------------------------------------
# Trajectory gate.


def test_trajectory_emit_namespaces_and_appends(tmp_path):
    path = str(tmp_path / "traj.json")
    tj.emit("m1", 10.0, "requests/s", backend="cpu", path=path)
    tj.emit("m2", 1.0, "frames/s", backend="tpu", path=path)
    doc = tj.load(path)
    assert [e["metric"] for e in doc["entries"]] == ["cpu:m1", "m2"]


def test_trajectory_emit_noop_without_target(tmp_path, monkeypatch):
    monkeypatch.delenv("RAFT_TRAJECTORY", raising=False)
    assert tj.emit("m", 1.0, "u") is None


def test_trajectory_check_bands():
    doc = {"schema": 1, "entries": [
        {"metric": "rps", "value": 8.0, "unit": "requests/s"},
        {"metric": "unpinned", "value": 1.0, "unit": "x"}]}
    bands = {"schema": 1,
             "bands": {"rps": {"value": 10.0, "rel_band": 0.2}}}
    res = tj.check(doc, bands)
    assert res.ok and res.checked == 1 and res.unpinned == ["unpinned"]
    doc["entries"][0]["value"] = 7.9  # below 10 * 0.8
    res = tj.check(doc, bands)
    assert not res.ok and "rps" in res.failures[0]
    doc["entries"][0]["value"] = 13.0  # above band: a note, never a fail
    res = tj.check(doc, bands)
    assert res.ok and res.notes


def test_trajectory_min_only_band_and_malformed_band():
    doc = {"schema": 1, "entries": [
        {"metric": "m", "value": 5.0, "unit": "x"}]}
    # min-only band: a legal explicit floor (no pinned center, no notes)
    bands = {"schema": 1, "bands": {"m": {"min": 1.0}}}
    res = tj.check(doc, bands)
    assert res.ok and res.checked == 1 and not res.notes
    doc["entries"][0]["value"] = 0.5
    res = tj.check(doc, bands)
    assert not res.ok and "explicit min" in res.failures[0]
    # a band with neither value nor min is malformed -> internal error
    # (exit 2 via the CLI), never a silent pass
    with pytest.raises(tj.TrajectoryError):
        tj.check(doc, {"schema": 1, "bands": {"m": {"rel_band": 0.2}}})


def test_trajectory_autopin_never_overwrites():
    doc = {"schema": 1, "entries": [
        {"metric": "a", "value": 5.0, "unit": "x"},
        {"metric": "b", "value": 2.0, "unit": "x"},
        {"metric": "cpu:c", "value": 9.0, "unit": "x"}]}
    bands = {"schema": 1, "bands": {"a": {"value": 4.0, "rel_band": 0.2}}}
    pinned = tj.autopin(doc, bands)
    assert pinned == ["b"]                       # a existed, cpu:c skipped
    assert bands["bands"]["a"]["value"] == 4.0   # untouched
    assert bands["bands"]["b"]["value"] == 2.0


def _traj_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.obs.trajectory"] + args,
        capture_output=True, text=True)


def test_trajectory_gate_cli_fails_out_of_band(tmp_path):
    """ISSUE 7 acceptance: a synthetic out-of-band requests/s entry fails
    the gate through the real CLI (the exact command release_gate.sh
    runs)."""
    traj = tmp_path / "TRAJECTORY.json"
    bands = tmp_path / "bands.json"
    traj.write_text(json.dumps({"schema": 1, "entries": [
        {"metric": "serve_requests_per_s_tiny", "value": 3.0,
         "unit": "requests/s", "source": "scratch/bench_serve.py"}]}))
    bands.write_text(json.dumps({"schema": 1, "bands": {
        "serve_requests_per_s_tiny": {"value": 10.0, "rel_band": 0.2}}}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 1, res.stdout + res.stderr
    assert "below the pinned floor" in res.stdout
    # in-band value passes the same gate
    traj.write_text(json.dumps({"schema": 1, "entries": [
        {"metric": "serve_requests_per_s_tiny", "value": 9.5,
         "unit": "requests/s"}]}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 0, res.stdout + res.stderr


def test_trajectory_gate_vacuous_warning_with_empty_bands(tmp_path):
    """ISSUE 12 satellite: an empty bands file has made the gate pass
    vacuously since PR 7 — the check must now SAY so, loudly, in the
    gate output, and stop saying so the moment a band exists."""
    traj = tmp_path / "TRAJECTORY.json"
    bands = tmp_path / "bands.json"
    traj.write_text(json.dumps({"schema": 1, "entries": [
        {"metric": "m", "value": 5.0, "unit": "x"}]}))
    warning = "0 bands pinned — gate is vacuous"
    # empty bands dict AND missing bands file both warn
    bands.write_text(json.dumps({"schema": 1, "bands": {}}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 0
    assert warning in res.stdout
    res = _traj_cli(["check", str(traj), "--bands",
                     str(tmp_path / "missing.json")])
    assert res.returncode == 0 and warning in res.stdout
    # the first pinned band silences it
    bands.write_text(json.dumps({"schema": 1, "bands": {
        "m": {"value": 5.0, "rel_band": 0.2}}}))
    res = _traj_cli(["check", str(traj), "--bands", str(bands)])
    assert res.returncode == 0
    assert warning not in res.stdout + res.stderr


def test_trajectory_gate_cli_malformed_is_rc2(tmp_path):
    traj = tmp_path / "TRAJECTORY.json"
    traj.write_text("{not json")
    res = _traj_cli(["check", str(traj), "--bands",
                     str(tmp_path / "missing_bands.json")])
    assert res.returncode == 2  # can never read as "clean"


# ---------------------------------------------------------------------------
# Serving integration: the batched span timeline (acceptance bar).


def test_batched_request_span_timeline_reconciles(tiny_params, tiny_cfg,
                                                  pair):
    """One request through the batched scheduler: >= 6 span kinds incl.
    per-segment advance ticks; tiled span sum == trace total == reported
    end-to-end latency, exactly, under FakeClock."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        valid_iters=4, segments=2, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        resp = svc.submit({"id": "r0", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    doc = sess.tracer.last()
    assert doc["meta"]["status"] == "ok"
    kinds = doc["summary"]["kinds"]
    # admission, queue_wait, upload, prepare, advance, epilogue, unpad
    assert set(kinds) >= {"admission", "queue_wait", "upload", "prepare",
                          "advance", "epilogue", "unpad"}
    assert kinds["advance"]["count"] == 2          # one per segment tick
    # prepare + 2 advances + epilogue, TICK injected device time each
    assert resp["elapsed_ms"] == pytest.approx(4 * TICK * 1e3)
    assert doc["summary"]["tiled_ms"] == pytest.approx(
        doc["summary"]["total_ms"])
    assert doc["summary"]["total_ms"] == pytest.approx(resp["elapsed_ms"])


def test_batched_deadline_exit_records_degrade_event(tiny_params, tiny_cfg,
                                                     pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4,
                        valid_iters=4, segments=2, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        # Warm + seed the EMAs (first request's invokes are warming runs).
        assert svc.submit({"id": "w0", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
        assert svc.submit({"id": "w1", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
        # Budget fits prepare + ONE advance (0.5 s) but not a second
        # (EMA predicts 0.25 * 1.15 overshoot past 0.6).
        resp = svc.submit({"id": "d", "left": pair[0], "right": pair[1],
                           "deadline_ms": 600.0}).result(timeout=120)
    assert resp["status"] == "ok"
    assert resp["quality"] == "reduced_iters:2"
    doc = sess.tracer.last()
    assert doc["meta"]["quality"] == "reduced_iters:2"
    kinds = doc["summary"]["kinds"]
    assert kinds["advance"]["count"] == 1
    assert "degrade" in kinds
    degrade = [s for s in doc["spans"] if s["kind"] == "degrade"][0]
    assert degrade["attrs"]["label"] == "reduced_iters:2"
    assert degrade["attrs"]["reason"] == "predicted_overshoot"


def test_sequential_request_span_timeline(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=4, workers=1)) as svc:
        resp = svc.submit({"id": "s0", "left": pair[0], "right": pair[1],
                           "deadline_ms": 60_000.0}).result(timeout=120)
    assert resp["status"] == "ok" and resp["quality"] == "full"
    doc = sess.tracer.last()
    kinds = doc["summary"]["kinds"]
    assert set(kinds) >= {"admission", "queue_wait", "prepare", "segment",
                          "unpad"}
    assert kinds["segment"]["count"] == 2
    assert doc["summary"]["tiled_ms"] == pytest.approx(
        doc["summary"]["total_ms"])
    assert doc["summary"]["total_ms"] == pytest.approx(resp["elapsed_ms"])


def test_disabled_tracing_serves_and_records_nothing(tiny_params, tiny_cfg,
                                                     pair):
    clock = FakeClock()
    tracer = Tracer(clock=clock, enabled=False, sink="")
    sess = make_session(tiny_params, tiny_cfg, clock=clock, tracer=tracer)
    with StereoService(sess, ServiceConfig(max_queue=4)) as svc:
        resp = svc.submit({"id": "n", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"
    assert tracer.timelines() == []


# ---------------------------------------------------------------------------
# /healthz derives from the registry (no surviving ad-hoc dicts).


def test_healthz_is_registry_derived(tiny_params, tiny_cfg, pair):
    sess = make_session(tiny_params, tiny_cfg)
    svc = StereoService(sess, ServiceConfig(max_queue=4))
    with svc:
        assert svc.submit({"id": "h", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
    st = svc.status()
    assert st["requests"]["ok"] == 1
    assert st["latency_ms"]["n"] == 1
    # Byte-for-byte: a registry mutation IS a /healthz mutation — there is
    # no second store the document could be reading.
    svc.registry.counter("raft_requests_total", outcome="ok").inc(41)
    assert svc.status()["requests"]["ok"] == 42
    sess.registry.counter("raft_session_requests_ok_total").inc(9)
    assert sess.metrics()["requests_ok"] == 10
    assert st["session"]["counts"]["requests_ok"] == 1  # pre-mutation copy
    # the legacy ad-hoc stores are gone
    assert not hasattr(svc, "_counts") and not hasattr(svc, "_latencies")
    assert not hasattr(sess, "_metrics")


def test_metrics_text_covers_all_subsystems(tiny_params, tiny_cfg, pair):
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, clock=clock)
    with StereoService(sess, ServiceConfig(max_queue=8)) as svc:
        assert svc.submit({"id": "m", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)[
                               "status"] == "ok"
    # After stop() the scheduler is quiesced: the registry is stable, and
    # /metrics keeps answering (scrapes outlive the worker threads).
    text = svc.metrics_text()
    assert '# TYPE raft_requests_total counter' in text
    assert 'raft_requests_total{outcome="ok"} 1' in text
    assert "raft_session_compiles_total" in text
    assert "raft_sched_ticks_total" in text
    assert "# TYPE raft_request_latency_seconds summary" in text
    assert "raft_program_calls_total" in text
    # scheduler /healthz numbers equal the rendered series
    b = svc.status()["batching"]
    assert f"raft_sched_ticks_total {b['ticks']}" in text


def test_program_device_host_split_recorded(tiny_params, tiny_cfg, pair):
    """Per-program-kind device-vs-host time: the injected device time
    lands in the device counter of the kind that ran it (steady-state
    invocations only; warmups are compile-inclusive and binned apart)."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    # Two identical requests: the first warms, the second is steady.
    sess.infer(*[p[None] for p in pair])
    sess.infer(*[p[None] for p in pair])
    dev = sess.registry.value("raft_program_device_seconds_total",
                              kind="full")
    warm = sess.registry.value("raft_program_warmup_seconds_total",
                               kind="full")
    assert dev == pytest.approx(TICK)   # one steady invocation
    assert warm == pytest.approx(TICK)  # one warming invocation
    assert sess.registry.value("raft_program_calls_total", kind="full") == 2


def test_breaker_trip_counter_in_registry(tiny_params, tiny_cfg, pair):
    from raft_stereo_tpu.faults import ServeFaultPlan
    plan = ServeFaultPlan(compile_errors={0: "mosaic:gru1632"})
    sess = make_session(tiny_params, tiny_cfg, plan=plan)
    sess.infer(*[p[None] for p in pair])  # walks one rung, then serves
    assert sess.registry.value("raft_breaker_trips_total",
                               rung="fuse_gru1632",
                               reason="compile_failure") == 1
    doc = sess.tracer.last()
    assert doc is None  # direct session.infer without a service trace


# ---------------------------------------------------------------------------
# graftscope-device: the program ledger (obs/ledger.py).


def _key(kind, b=1, h=64, w=96, iters=2):
    return (kind, b, h, w, iters, ("fp",))


def test_ledger_scan_scale_estimates():
    """Raw compiler numbers are preserved; per-invocation estimates apply
    the declared scan scale; 'full'-style scan-opaque rows get NO
    estimate (absent beats 32x wrong)."""
    led = lg.ProgramLedger()
    adv = led.record(_key("advance", b=2, iters=4), kind="advance", b=2,
                     h=64, w=96, iters=4, scan_scale=4,
                     analysis={"flops": 100.0, "bytes_accessed": 10.0,
                               "argument_bytes": 5.0, "output_bytes": 3.0,
                               "temp_bytes": 2.0, "alias_bytes": 1.0})
    assert adv.flops == 100.0 and adv.flops_est == 400.0
    assert adv.bytes_est == 40.0
    assert adv.peak_hbm_bytes == 9.0  # arg + out + temp - alias
    prep = led.record(_key("prepare", iters=0), kind="prepare", iters=0,
                      scan_scale=1, analysis={"flops": 7.0})
    assert prep.flops_est == 7.0
    full = led.record(_key("full", iters=32), kind="full", iters=32,
                      scan_scale=None, analysis={"flops": 9.0})
    assert full.flops_est is None and full.bytes_est is None


def test_ledger_absent_and_partial_analysis():
    """Backends that report nothing (or only some keys) yield absent
    fields — never zeros that would poison sums or ratios."""
    led = lg.ProgramLedger()
    empty = led.record(_key("prepare"), kind="prepare", scan_scale=1,
                       analysis={})
    assert empty.flops is None and empty.flops_est is None
    assert empty.peak_hbm_bytes is None  # unknown, not 0
    partial = led.record(_key("segment", iters=2), kind="segment", iters=2,
                         scan_scale=2, analysis={"flops": 5.0})
    assert partial.flops_est == 10.0
    assert partial.bytes_accessed is None and partial.bytes_est is None
    assert partial.peak_hbm_bytes is None
    assert partial.intensity() is None
    assert partial.roofline((1e12, 1e11)) is None


def test_ledger_attribution_never_divides_blind():
    """MFU is absent unless flops, device seconds AND a chip peak all
    exist and are positive — zero device-seconds (the satellite bar) and
    off-table devices (CPU) must not produce a number."""
    led = lg.ProgramLedger()
    led.record(_key("segment", iters=2), kind="segment", iters=2,
               scan_scale=2, analysis={"flops": 50.0,
                                       "bytes_accessed": 10.0})
    reg = MetricsRegistry()
    reg.counter("raft_program_flops_total", kind="segment").inc(100.0)
    # zero device seconds -> absent, no ZeroDivisionError
    att = led.attribution(reg, peaks=(1e12, 1e11))
    assert att["segment"]["mfu"] is None
    reg.counter("raft_program_device_seconds_total",
                kind="segment").inc(2.0)
    att = led.attribution(reg, peaks=(1e12, 1e11))
    assert att["segment"]["mfu"] == pytest.approx(100.0 / 2.0 / 1e12)
    # off the chip table (CPU): absent even with full counters
    att = led.attribution(reg, device_kind="cpu")
    assert att["segment"]["mfu"] is None
    # seconds but no flops (scan-opaque kind): absent
    reg.counter("raft_program_device_seconds_total", kind="full").inc(1.0)
    att = led.attribution(reg, peaks=(1e12, 1e11))
    assert att["full"]["mfu"] is None


def test_chip_peaks_table():
    f, bw = lg.chip_peaks("TPU v5 lite chip")
    assert f == 197e12 and bw == 819e9
    assert lg.chip_peaks("TPU v4") == (275e12, 1228e9)
    assert lg.chip_peaks("cpu") is None
    assert lg.chip_peaks(None) is None
    assert lg.hbm_capacity("TPU v5e") == 16 * 2**30
    assert lg.hbm_capacity("cpu") is None


def test_analyze_compiled_real_program():
    """The extraction works against a real jax Compiled on this backend
    (flops + argument bytes present on CPU)."""
    import jax.numpy as jnp

    def f(x):
        return (jnp.sin(x) * 2.0).sum()

    compiled = jax.jit(f).lower(jnp.ones((32, 32), jnp.float32)).compile()
    a = lg.analyze_compiled(compiled)
    assert a["flops"] and a["flops"] > 0
    assert a["argument_bytes"] == 32 * 32 * 4


def test_analyze_compiled_graceful_on_junk():
    """A backend object whose analyses raise or return nothing yields
    all-None — the fallback path the tentpole demands."""

    class Junk:
        def cost_analysis(self):
            raise RuntimeError("not supported")

        def memory_analysis(self):
            return None

    a = lg.analyze_compiled(Junk())
    assert all(v is None for v in a.values())

    class Weird:
        def cost_analysis(self):
            return [{"flops": -1.0}]  # XLA's "unknown" sentinel

        def memory_analysis(self):
            return object()  # no size attributes at all

    a = lg.analyze_compiled(Weird())
    assert all(v is None for v in a.values())


def _ledger_cli(args):
    return subprocess.run(
        [sys.executable, "-m", "raft_stereo_tpu.obs.ledger"] + args,
        capture_output=True, text=True)


def test_ledger_report_cli(tmp_path):
    led = lg.ProgramLedger()
    key = _key("prepare")
    led.record(key, kind="prepare", h=64, w=96, scan_scale=1,
               analysis={"flops": 5.0, "argument_bytes": 10.0,
                         "output_bytes": 2.0, "temp_bytes": 1.0,
                         "alias_bytes": 0.0})
    path = tmp_path / "LEDGER.json"

    lg.save_doc(led.to_doc(cache_keys=[key], backend="cpu"), str(path))
    res = _ledger_cli(["report", str(path)])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "complete" in res.stdout

    # a cached program with no row fails the report (the gate bar)
    lg.save_doc(led.to_doc(cache_keys=[key, _key("segment")],
                           backend="cpu"), str(path))
    res = _ledger_cli(["report", str(path)])
    assert res.returncode == 1
    assert "no ledger row" in res.stdout

    path.write_text("{not json")
    res = _ledger_cli(["report", str(path)])
    assert res.returncode == 2  # malformed can never read as clean


def test_session_ledger_covers_cache_and_reports_hbm(tiny_params, tiny_cfg,
                                                     pair):
    """Every compiled program gets a ledger row at warm time; /healthz
    reports cache HBM per shape bucket and the gauges follow."""
    sess = make_session(tiny_params, tiny_cfg)
    sess.infer(*[p[None] for p in pair])
    doc = sess.ledger_doc()
    assert doc["complete"] and not doc["missing"]
    assert len(doc["rows"]) == len(doc["cache"]) == 1
    row = doc["rows"][0]
    assert row["kind"] == "full" and row["flops"] > 0
    # CPU's compiled memory analysis reports argument/output sizes, so
    # the cache-HBM account is positive and bucketed by padded shape.
    st = sess.status()["ledger"]
    assert st["rows"] == 1
    by_bucket = st["cache_hbm"]["by_bucket"]
    assert list(by_bucket) == ["64x64"]  # H=40,W=60 pads to 64x64
    assert by_bucket["64x64"] > 0
    assert sess.registry.value("raft_cache_hbm_bytes",
                               bucket="64x64") == by_bucket["64x64"]
    assert sess.registry.value(
        "raft_cache_hbm_total_bytes") == st["cache_hbm"]["total_bytes"]


def test_eviction_drops_ledger_row_and_names_it(tiny_params, tiny_cfg,
                                                pair, caplog):
    """LRU eviction drops the ledger row, logs a line NAMING it, and the
    bucket gauge returns to zero when its programs all leave."""
    import logging as _logging
    sess = make_session(tiny_params, tiny_cfg, max_programs=1)
    sess.infer(*[p[None] for p in pair])
    assert len(sess.ledger) == 1
    big = np.zeros((72, 100, 3), np.float32)  # pads to 96x128
    with caplog.at_level(_logging.INFO,
                         logger="raft_stereo_tpu.serve.session"):
        sess.infer(big[None].copy(), big[None].copy())
    assert len(sess.ledger) == 1  # old row dropped with its program
    assert sess.ledger_doc()["complete"]
    msgs = [r.message for r in caplog.records
            if "evicted program" in r.message]
    assert msgs and "full@b1:64x64" in msgs[0]
    assert sess.registry.value("raft_cache_hbm_bytes", bucket="64x64") == 0
    assert sess.registry.value("raft_cache_hbm_bytes",
                               bucket="96x128") > 0


def test_session_mfu_join_with_injected_peaks(tiny_params, tiny_cfg, pair):
    """The MFU join end-to-end on CPU: steady segmented invocations
    accumulate ledger flops per kind; attribution with injected peaks
    yields a positive MFU and publishes the gauge; scan-opaque and
    warmup-only kinds stay absent."""
    clock = FakeClock()
    sess = make_session(tiny_params, tiny_cfg, plan=slow_plan(),
                        clock=clock)
    for _ in range(2):  # first call warms, second is steady
        sess.infer(*[p[None] for p in pair],
                   deadline=clock.now() + 1e6)
    assert sess.registry.value("raft_program_flops_total",
                               kind="segment") > 0
    att = sess.attribution(peaks=(1e12, 1e11))
    assert att["segment"]["mfu"] is not None and att["segment"]["mfu"] > 0
    assert att["segment"]["roofline"] in ("compute-bound", "hbm-bound")
    assert sess.registry.value("raft_program_mfu",
                               kind="segment") == att["segment"]["mfu"]
    # without injected peaks this is a CPU host: absent, never fabricated
    assert sess.attribution()["segment"]["mfu"] is None


# ---------------------------------------------------------------------------
# graftscope-device: the SLO flight recorder (obs/flight.py).


def test_flight_disabled_without_dir(monkeypatch):
    monkeypatch.delenv("RAFT_FLIGHT_DIR", raising=False)
    rec = FlightRecorder()
    assert not rec.enabled
    assert rec.record({"x": 1}) is None
    assert rec.status()["skipped"] == 1


def test_flight_bounded_oldest_first(tmp_path):
    rec = FlightRecorder(out_dir=str(tmp_path), limit=2)
    for i in range(4):
        assert rec.record({"i": i}, trace_id=f"req-{i}") is not None
    paths = rec.records()
    assert len(paths) == 2
    docs = [json.loads(open(p).read()) for p in paths]
    assert [d["i"] for d in docs] == [2, 3]  # oldest evicted first
    st = rec.status()
    assert st["recorded"] == 4 and st["evicted"] == 2
    # a fresh recorder over the same dir continues the sequence: the
    # eviction order survives restarts
    rec2 = FlightRecorder(out_dir=str(tmp_path), limit=2)
    rec2.record({"i": 4}, trace_id="req-4")
    assert json.loads(open(rec2.records()[-1]).read())["i"] == 4


def test_flight_sink_failure_disables(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not dir")
    rec = FlightRecorder(out_dir=str(blocker))
    assert rec.record({"x": 1}) is None  # must not raise
    assert not rec.enabled  # sink dropped, like RAFT_TRACE
    assert rec.record({"x": 2}) is None
    assert rec.status()["skipped"] == 1


def test_flight_record_on_slo_breach_reconciles(tmp_path, tiny_params,
                                                tiny_cfg, pair):
    """ISSUE 8 acceptance: an injected SLO breach under FakeClock yields
    EXACTLY ONE flight record whose span sum reconciles with the reported
    latency, carrying the ledger rows of every program the request rode."""
    clock = FakeClock()
    flight = FlightRecorder(out_dir=str(tmp_path), limit=8)
    sess = make_session(tiny_params, tiny_cfg, max_batch=4, valid_iters=4,
                        segments=2, plan=slow_plan(), clock=clock,
                        flight=flight)
    with StereoService(sess, ServiceConfig(max_queue=8,
                                           slo_ms=100.0)) as svc:
        resp = svc.submit({"id": "r0", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"
    # prepare + 2 advances + epilogue at TICK injected device-time each =
    # 1000 ms >> the 100 ms SLO.
    assert resp["elapsed_ms"] == pytest.approx(4 * TICK * 1e3)
    paths = flight.records()
    assert len(paths) == 1  # exactly one record for the one breach
    doc = json.loads(open(paths[0]).read())
    assert doc["reasons"] == ["latency_slo"]
    s = doc["trace"]["summary"]
    assert s["tiled_ms"] == pytest.approx(s["total_ms"])
    assert s["total_ms"] == pytest.approx(resp["elapsed_ms"])
    kinds = {p["kind"] for p in doc["programs"]}
    assert {"prepare", "advance", "epilogue"} <= kinds
    assert doc["metrics"]["raft_requests_total"]["series"]
    assert doc["breaker"] is not None


def test_flight_record_on_breaker_trip(tmp_path, tiny_params, tiny_cfg,
                                       pair):
    flight = FlightRecorder(out_dir=str(tmp_path))
    plan = ServeFaultPlan(compile_errors={0: "mosaic:gru1632"})
    sess = make_session(tiny_params, tiny_cfg, plan=plan, flight=flight)
    with StereoService(sess, ServiceConfig(max_queue=4)) as svc:
        resp = svc.submit({"id": "t", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"  # served one rung down
    paths = flight.records()
    assert len(paths) == 1
    doc = json.loads(open(paths[0]).read())
    assert doc["reasons"] == ["breaker_trip"]
    assert doc["breaker"]["tripped"]


def test_flight_record_on_nonfinite_output(tmp_path, tiny_params, tiny_cfg,
                                           pair):
    flight = FlightRecorder(out_dir=str(tmp_path))
    plan = ServeFaultPlan(poison_outputs=(0,))
    sess = make_session(tiny_params, tiny_cfg, plan=plan, flight=flight)
    # retry_budget=0: a first non-finite output is transient under the
    # r13 retry contract (re-served once); this test pins the BREACH
    # record, so serve the poisoned attempt as the final answer.
    with StereoService(sess, ServiceConfig(max_queue=4,
                                           retry_budget=0)) as svc:
        resp = svc.submit({"id": "p", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "error"
    assert resp["code"] == "nonfinite_output"
    paths = flight.records()
    assert len(paths) == 1
    doc = json.loads(open(paths[0]).read())
    assert "nonfinite_output" in doc["reasons"]


def test_no_flight_record_when_healthy(tmp_path, tiny_params, tiny_cfg,
                                       pair):
    flight = FlightRecorder(out_dir=str(tmp_path))
    sess = make_session(tiny_params, tiny_cfg, flight=flight)
    with StereoService(sess, ServiceConfig(max_queue=4,
                                           slo_ms=1e9)) as svc:
        resp = svc.submit({"id": "h", "left": pair[0],
                           "right": pair[1]}).result(timeout=120)
    assert resp["status"] == "ok"
    assert flight.records() == []


# ---------------------------------------------------------------------------
# Prometheus exposition escaping (the hostile-label satellite).


def test_metrics_prometheus_hostile_label_golden():
    """Backslash, quote and newline in label values AND in help text must
    render per the exposition-format escaping rules — raw, they corrupt
    the line protocol for every scraper."""
    r = MetricsRegistry()
    r.counter("h_total", 'help with \\ back and\nnewline',
              path='a\\b"c\nd').inc()
    golden = (
        '# HELP h_total help with \\\\ back and\\nnewline\n'
        '# TYPE h_total counter\n'
        'h_total{path="a\\\\b\\"c\\nd"} 1\n')
    assert r.render_prometheus() == golden


# ---------------------------------------------------------------------------
# Trajectory failure diagnosis (graftscope-device part d).


def test_trajectory_autopin_pins_diagnostic_extras():
    doc = {"schema": 1, "entries": [
        {"metric": "fps", "value": 5.0, "unit": "frames/s",
         "extra": {"flops": 100.0, "mfu": 0.3, "note": "text"}}]}
    bands = {"schema": 1, "bands": {}}
    assert tj.autopin(doc, bands) == ["fps"]
    # numeric diagnostic keys pinned, free-text extras dropped
    assert bands["bands"]["fps"]["extra"] == {"flops": 100.0, "mfu": 0.3}


def test_trajectory_failure_diagnosis_lines():
    bands = {"schema": 1, "bands": {
        "fps": {"value": 10.0, "rel_band": 0.2,
                "extra": {"flops": 100.0}}}}

    def fail_with(extra):
        entry = {"metric": "fps", "value": 5.0, "unit": "frames/s"}
        if extra is not None:
            entry["extra"] = extra
        res = tj.check({"schema": 1, "entries": [entry]}, bands)
        assert not res.ok
        return res.failures[0]

    # flops moved -> the program itself changed
    assert "program flops changed" in fail_with({"flops": 150.0})
    # flops unchanged -> the machine/env drifted
    assert "machine/env drift" in fail_with({"flops": 100.0})
    assert "machine/env drift" in fail_with({"flops": 101.0})  # in rtol
    # no telemetry -> the absence is stated, still one diagnosis line
    assert "no pinned flops extra" in fail_with(None)


# ---------------------------------------------------------------------------
# Review-round regressions (r12).


def test_compile_failure_still_records_ledger_row(tiny_params, tiny_cfg):
    """A REAL compile failure propagates to the breaker, but the cached
    program must still get a (empty) ledger row — a server healthily
    degraded one rung down must not false-fail the completeness gate
    over the rung that refused to compile."""
    from raft_stereo_tpu.serve.session import _Program
    sess = make_session(tiny_params, tiny_cfg)

    class BoomJit:
        def lower(self, *a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                               "allocating on device")

    key = sess.cache_key("full", 64, 64, 4)
    prog = _Program(key, BoomJit(), "full", {})
    with pytest.raises(RuntimeError):
        sess._aot_compile(prog, ())
    row = sess.ledger.row(key)
    assert row is not None
    assert row.flops is None and row.peak_hbm_bytes is None


def test_flight_record_on_deadline_expired_in_queue(tmp_path, tiny_params,
                                                    tiny_cfg, pair):
    """A queue-expired rejection is a breach (its queue_wait timeline is
    exactly what an operator debugging backlog needs) — not only the
    served-but-late case."""
    flight = FlightRecorder(out_dir=str(tmp_path))
    sess = make_session(tiny_params, tiny_cfg, flight=flight)
    with StereoService(sess, ServiceConfig(max_queue=4)) as svc:
        resp = svc.submit({"id": "d", "left": pair[0], "right": pair[1],
                           "deadline_ms": 0.0}).result(timeout=120)
    assert resp["status"] == "rejected"
    assert resp["code"] == "deadline_exceeded_in_queue"
    paths = flight.records()
    assert len(paths) == 1
    doc = json.loads(open(paths[0]).read())
    assert doc["reasons"] == ["deadline_missed"]
    assert any(s["kind"] == "queue_wait" for s in doc["trace"]["spans"])


def test_ledger_report_cli_malformed_rows_rc2(tmp_path):
    """Element-level corruption (a rows entry that is not an id-carrying
    dict) is exit 2 — malformed, never a misclassified completeness
    failure with a traceback."""
    path = tmp_path / "LEDGER.json"
    path.write_text(json.dumps(
        {"schema": 1, "rows": [None], "cache": [], "missing": []}))
    res = _ledger_cli(["report", str(path)])
    assert res.returncode == 2, res.stdout + res.stderr
    assert "malformed ledger row" in res.stderr
